"""In-process broker core: ordered topic logs with offset fetch.

Semantics mirror what the reference actually uses of Kafka
(/root/reference/topic.js:14-25, exchange_test.js:14-16, consumer.js:13-17):
- named topics created explicitly (1 partition each — the provisioner
  pins `numPartitions: 1`, so each topic is ONE totally-ordered log);
- producers append (key, value) string records;
- consumers fetch by offset (fromBeginning => offset 0) and poll
  blocking with a timeout.

Thread-safe; `fetch` blocks on a condition variable until data arrives
or the timeout lapses — the poll-loop shape of a Kafka consumer without
the broker round-trip.

`persist_dir` makes the logs DURABLE: each topic appends to an
append-only JSONL file and the broker reloads every topic at startup —
the Kafka-retains-the-log property the engine's checkpoint/resume
contract depends on (the restored MatchIn offset must still address the
same records after a broker restart). A torn trailing line (crash mid-
append) is dropped on reload.

Exactly-once visible output (the path the reference commented out at
KProcessor.java:29) is built from two broker-side rules applied to
records carrying an ``(epoch, out_seq)`` produce stamp:

- **fencing**: a produce stamped with an epoch below the broker's fence
  raises BrokerFenced — a deposed leader can never make a write
  visible. The fence advances to any higher epoch seen (produce or an
  explicit ``fence()`` from a newly promoted leader) and is recovered
  from the stamps in the log on reload.
- **idempotent produce**: per topic, a stamped record whose ``out_seq``
  is at or below the durable watermark is suppressed (no append,
  ``dup_suppressed`` counts it) — a restarted leader deterministically
  re-produces its post-snapshot tail with the SAME stamps, so the
  durable log itself stays duplicate-free.

Unstamped produces behave exactly as before; log lines stay
``[key,value]`` for them and gain two elements (``[key,value,epoch,
out_seq]``) only when stamped, so pre-existing logs load unchanged.
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import threading
from typing import Dict, IO, List, Optional

from kme_tpu import faults


class BrokerError(RuntimeError):
    pass


class BrokerOverload(BrokerError):
    """The bounded ingress queue shed this produce (wire-level
    `rej_overload`, wire.py rej table code 9). Producers should back
    off and retry; the broker never blocks them.

    When the adaptive controller sheds (rather than the binary
    `max_lag` bound), `backoff_ms` carries the AIMD producer hint —
    pause at least this long before re-offering — and `detail` the
    observed backlog / threshold / degradation state for REJ
    annotation. Both stay None on the binary path."""

    code = "rej_overload"
    backoff_ms: Optional[int] = None
    detail: Optional[dict] = None


class BrokerFenced(BrokerError):
    """A produce stamped with a stale leader epoch. Not retryable: the
    producer has been deposed and must exit so its supervisor can
    restart it under a fresh epoch (serve exits 75)."""

    code = "fenced"


@dataclasses.dataclass(frozen=True)
class Record:
    offset: int
    key: Optional[str]
    value: str
    epoch: Optional[int] = None
    out_seq: Optional[int] = None
    # broker-admission wall clock, microseconds since epoch — the
    # INTENDED-START stamp for coordinated-omission-safe latency
    # (stamped at produce time, before any queueing the consumer's
    # dequeue rate would hide). In-memory only: log rows keep their
    # [key,value(,epoch,out_seq)] shape, so records reloaded after a
    # restart carry ats=None and latency attribution simply skips them.
    ats: Optional[int] = None
    # transport-advisory trace word (wire FLAG_TID / produce "tid").
    # In-memory only, like ats: the AUTHORITATIVE trace id is always
    # derived from durable identity (dtrace.trace_id over the record's
    # offset), so traces survive reloads that drop this field. Carried
    # ids exist so clients can correlate their own sends with the
    # derived waterfalls (loadgen RTT sampling).
    tid: Optional[int] = None


class _Topic:
    def __init__(self, partitions: int = 1,
                 logfile: Optional[IO] = None) -> None:
        self.partitions = partitions
        self.log: List[Record] = []
        self.logfile = logfile
        # idempotent-produce watermark: highest out_seq made durable on
        # this topic (-1 = no stamped record yet); recovered from the
        # log stamps on reload.
        self.max_out_seq = -1


# -- adaptive overload control (SEDA-style, Welsh et al. SOSP '01) ---------
#
# The binary `max_lag` bound above sheds EVERYTHING past a fixed backlog —
# including the cancels and payouts that would actually shrink the book.
# The controller replaces that cliff with a degradation state machine and
# priority-aware admission; the binary path stays available and unchanged.

# priority classes: lower admits longer. Book-DRAINING ops are the last
# thing an overloaded engine should refuse (each admitted cancel/payout
# REMOVES resting state); ADMIN ops are cheap and rare; fresh ORDERS are
# what grows the backlog, so they shed first.
CLS_DRAIN = 0    # CANCEL, PAYOUT, REMOVE_SYMBOL
CLS_ADMIN = 1    # CREATE_BALANCE, TRANSFER, ADD_SYMBOL
CLS_ORDER = 2    # BUY, SELL, and anything unparseable

_CLS_BY_ACTION = {4: CLS_DRAIN, 200: CLS_DRAIN, 1: CLS_DRAIN,
                  100: CLS_ADMIN, 101: CLS_ADMIN, 0: CLS_ADMIN}


def classify_produce(value: str):
    """(priority class, oid, aid) of one wire value. Malformed input is
    CLS_ORDER — never give garbage the drain-priority fast lane."""
    try:
        doc = json.loads(value)
        action = int(doc.get("action"))
        oid = int(doc.get("oid") or 0)
        aid = int(doc.get("aid") or 0)
    except (ValueError, TypeError, AttributeError):
        return CLS_ORDER, 0, 0
    return _CLS_BY_ACTION.get(action, CLS_ORDER), oid, aid


def classify_actions(actions):
    """Vectorized _CLS_BY_ACTION over an int action column — the binary
    produce path's classifier (frames already carry decoded columns, so
    admission never touches JSON there). int8 class per row."""
    import numpy as np

    acts = np.asarray(actions)
    out = np.full(len(acts), CLS_ORDER, np.int8)
    for a, c in _CLS_BY_ACTION.items():
        out[acts == a] = c
    return out


class OverloadController:
    """Degradation state machine with hysteresis + priority admission.

    States (gauge codes): 0 normal — admit everything; 1 shedding —
    admit DRAIN/ADMIN, ration ORDER flow (linear ramp between the low
    and drain watermarks) under per-account fairness caps; 2 draining —
    admit ONLY book-draining ops until the backlog falls back below the
    high watermark.

    Transitions are driven by the observed backlog (produce side) and
    an EWMA of admission-to-produce latency (fed by the service):

        normal   -> shedding   backlog >= high_lag OR latency > budget
        shedding -> draining   backlog >= drain_lag
        shedding -> normal     backlog <= low_lag AND latency cool
        draining -> shedding   backlog <  high_lag

    (draining exits only through shedding — the hysteresis that stops
    the controller flapping at a watermark.)

    The AIMD producer contract rides `BrokerOverload.backoff_ms`: each
    shed grows the hint additively (bounded); each admitted record in
    normal state halves it. Producers sleep >= the hint before
    re-offering and grow their offered rate additively afterwards.

    Deterministic by construction: no wall clock, no RNG — the same
    (value, backlog) sequence yields the same decisions, which is what
    lets simulate_overload() gate shed_frac in CI at zero noise.
    """

    NORMAL, SHEDDING, DRAINING = 0, 1, 2
    STATE_NAMES = ("normal", "shedding", "draining")

    def __init__(self, high_lag: int, low_lag: Optional[int] = None,
                 drain_lag: Optional[int] = None,
                 p99_budget_ms: Optional[float] = None,
                 account_cap: float = 0.5, fair_window: int = 128,
                 backoff_step_ms: int = 5,
                 backoff_max_ms: int = 2000) -> None:
        if high_lag < 2:
            raise ValueError("overload high_lag must be >= 2")
        self.high_lag = int(high_lag)
        self.low_lag = (max(1, self.high_lag // 2) if low_lag is None
                        else int(low_lag))
        self.drain_lag = (self.high_lag * 2 if drain_lag is None
                          else int(drain_lag))
        if not (self.low_lag < self.high_lag <= self.drain_lag):
            raise ValueError("need low_lag < high_lag <= drain_lag")
        self.p99_budget_ms = p99_budget_ms
        self.account_cap = float(account_cap)
        self.fair_window = int(fair_window)
        self.backoff_step_ms = int(backoff_step_ms)
        self.backoff_max_ms = int(backoff_max_ms)
        self.state = self.NORMAL
        self.backoff_ms = 0
        self.lat_ewma_ms = 0.0
        self.transitions = 0
        self.admitted_by_class = {c: 0 for c in range(3)}
        self.shed_by_class = {c: 0 for c in range(3)}
        self.fairness_sheds = 0
        # ration tokens: in shedding, each arriving ORDER earns
        # (drain_lag - backlog) tokens out of (drain_lag - low_lag);
        # one admit costs a full span. Pure integer arithmetic.
        self._tokens = 0
        # sliding window of recently admitted ORDER aids for the
        # fairness cap (one flooder can't take the whole ration)
        self._fair_ring: List[int] = []
        self._fair_pos = 0
        self._fair_counts: Dict[int, int] = {}
        # flight-recorder seam: called as cb(prev_code, new_code) on
        # every state transition. The controller stays a pure state
        # machine — the callback observes decisions, never makes them,
        # and a raising callback cannot wedge admission
        self.on_transition = None

    # -- feeds ---------------------------------------------------------

    def observe_latency(self, seconds: float) -> None:
        """Admission-to-produce latency feed (service e2e stage)."""
        ms = seconds * 1000.0
        self.lat_ewma_ms += 0.2 * (ms - self.lat_ewma_ms)

    def _lat_hot(self) -> bool:
        return (self.p99_budget_ms is not None
                and self.lat_ewma_ms > self.p99_budget_ms)

    # -- state machine -------------------------------------------------

    def _to(self, state: int) -> None:
        if state != self.state:
            prev, self.state = self.state, state
            self.transitions += 1
            cb = self.on_transition
            if cb is not None:
                try:
                    cb(prev, state)
                except Exception:
                    pass

    def _update_state(self, backlog: int) -> None:
        if self.state == self.NORMAL:
            if backlog >= self.drain_lag:
                self._to(self.DRAINING)
            elif backlog >= self.high_lag or self._lat_hot():
                self._to(self.SHEDDING)
        elif self.state == self.SHEDDING:
            if backlog >= self.drain_lag:
                self._to(self.DRAINING)
            elif backlog <= self.low_lag and not self._lat_hot():
                self._to(self.NORMAL)
        else:
            if backlog < self.high_lag:
                self._to(self.SHEDDING)

    # -- admission -----------------------------------------------------

    def _fair_blocked(self, aid: int) -> bool:
        n = len(self._fair_ring)
        if n < 8:        # no meaningful share signal yet
            return False
        return self._fair_counts.get(aid, 0) > self.account_cap * n

    def _fair_admit(self, aid: int) -> None:
        if self.fair_window <= 0:
            return
        if len(self._fair_ring) < self.fair_window:
            self._fair_ring.append(aid)
        else:
            old = self._fair_ring[self._fair_pos]
            c = self._fair_counts.get(old, 0) - 1
            if c <= 0:
                self._fair_counts.pop(old, None)
            else:
                self._fair_counts[old] = c
            self._fair_ring[self._fair_pos] = aid
            self._fair_pos = (self._fair_pos + 1) % self.fair_window
        self._fair_counts[aid] = self._fair_counts.get(aid, 0) + 1

    def _shed(self, cls: int, oid: int, aid: int, backlog: int,
              threshold: int, fairness: bool = False):
        self.shed_by_class[cls] += 1
        if fairness:
            self.fairness_sheds += 1
        self.backoff_ms = min(self.backoff_max_ms,
                              self.backoff_ms + self.backoff_step_ms)
        return False, {"backlog": backlog, "threshold": threshold,
                       "state": self.STATE_NAMES[self.state],
                       "cls": cls, "oid": oid, "aid": aid,
                       "backoff_ms": self.backoff_ms,
                       "fairness": fairness}

    def admit(self, value: str, backlog: int):
        """One admission decision: (True, None) or (False, detail)."""
        cls, oid, aid = classify_produce(value)
        return self.admit_classified(cls, oid, aid, backlog)

    def admit_classified(self, cls: int, oid: int, aid: int,
                         backlog: int):
        """admit() with the (class, oid, aid) triple already known —
        the binary produce path classifies whole batches from the
        decoded action column (classify_actions) and never pays a
        json.loads per record. Same decisions, same counters."""
        self._update_state(backlog)
        if self.state == self.NORMAL:
            self.admitted_by_class[cls] += 1
            self.backoff_ms //= 2
            return True, None
        if self.state == self.DRAINING:
            if cls == CLS_DRAIN:
                self.admitted_by_class[cls] += 1
                return True, None
            return self._shed(cls, oid, aid, backlog, self.drain_lag)
        # SHEDDING
        if cls != CLS_ORDER:
            self.admitted_by_class[cls] += 1
            return True, None
        if self._fair_blocked(aid):
            return self._shed(cls, oid, aid, backlog, self.high_lag,
                              fairness=True)
        span = self.drain_lag - self.low_lag
        room = max(0, self.drain_lag - backlog)
        self._tokens += min(room, span)
        if self._tokens >= span:
            self._tokens -= span
            self.admitted_by_class[cls] += 1
            self._fair_admit(aid)
            return True, None
        return self._shed(cls, oid, aid, backlog, self.high_lag)

    def snapshot(self) -> dict:
        return {"state": self.STATE_NAMES[self.state],
                "state_code": self.state,
                "backoff_ms": self.backoff_ms,
                "lat_ewma_ms": round(self.lat_ewma_ms, 3),
                "transitions": self.transitions,
                "admitted_by_class": dict(self.admitted_by_class),
                "shed_by_class": dict(self.shed_by_class),
                "fairness_sheds": self.fairness_sheds}


def simulate_overload(values: List[str], windows, controller:
                      OverloadController, drain_per_msg: float = 2.0
                      ) -> dict:
    """Deterministic arrival/drain replay of the admission logic — the
    CI-gated half of the storm suite (live chaos runs prove parity and
    SLOs; this proves the shed POLICY never drifts unnoticed).

    Each message is one arrival tick. At base pacing the consumer
    drains `drain_per_msg` records per tick; inside a burst window
    (lo, hi, mult) arrivals outpace the drain mult-fold, so the drain
    credit is scaled by 1/mult. No wall clock, no RNG: the same
    (values, windows, controller params) triple yields bit-identical
    results on any machine.
    """
    backlog = 0
    credit = 0.0
    admitted_idx: List[int] = []
    max_backlog = 0
    for i, v in enumerate(values):
        mult = 1
        for lo, hi, m in windows:
            if lo <= i < hi:
                mult = m
                break
        credit += drain_per_msg / mult
        drains = int(credit)
        if drains:
            credit -= drains
            backlog = max(0, backlog - drains)
        ok, _ = controller.admit(v, backlog)
        if ok:
            admitted_idx.append(i)
            backlog += 1
            if backlog > max_backlog:
                max_backlog = backlog
    total = len(values)
    shed = total - len(admitted_idx)
    return {"total": total, "admitted": len(admitted_idx),
            "shed": shed,
            "shed_frac": (shed / total) if total else 0.0,
            "max_backlog": max_backlog,
            "admitted_idx": admitted_idx,
            "controller": controller.snapshot()}


def _flush_log_lines(logfile, lines: List[str]) -> None:
    """The batched durable-write exit point for produce_frames: ONE
    write + flush for a whole admitted prefix. Deliberately outside
    the produce_frames lint hot-scope — this is the sanctioned place
    for the blocking I/O, so anything blocking reappearing inside the
    per-record loop fails KME-H001."""
    logfile.write("".join(lines))
    logfile.flush()


class InProcessBroker:
    """The broker API the rest of the bridge codes against. The TCP
    client (tcp.TcpBroker) implements the same three methods."""

    def __init__(self, persist_dir: Optional[str] = None,
                 max_lag: Optional[int] = None,
                 overload: Optional[OverloadController] = None,
                 clock=None) -> None:
        from kme_tpu.bridge.clock import WALL

        # the clock seam (bridge/clock.py): admission stamps (``ats``)
        # come off this object so a simulated broker stamps virtual
        # microseconds deterministically
        self._clock = clock or WALL
        self._topics: Dict[str, _Topic] = {}
        self._lock = threading.Lock()
        self._data = threading.Condition(self._lock)
        self._persist_dir = persist_dir
        # bounded ingress: once a consumer has committed a watermark for
        # a topic (MatchService commits MatchIn each batch), producing
        # more than `max_lag` records past it is refused with
        # BrokerOverload instead of growing the backlog without bound —
        # shed load, never stall. Topics without a watermark (MatchOut)
        # are unbounded.
        self._max_lag = max_lag
        self._commits: Dict[str, int] = {}
        self.overload_rejects = 0
        # ingress encoding mix + decode cost. JSON produces count only
        # on admission-bounded topics (a committed watermark marks a
        # topic as ingress — MatchOut publishes are never counted);
        # produce_frames is definitionally ingress and always counts.
        # Feeds the wire_binary_frac / parse_ns_per_msg gauges
        # (service).
        self.wire_binary_records = 0
        self.wire_json_records = 0
        self.wire_parse_ns = 0
        # adaptive overload control: an OverloadController makes the
        # shed decision priority-aware (same arming rule as max_lag —
        # only topics with a committed watermark are bounded). The
        # binary max_lag check above it is untouched and wins first.
        self.overload = overload
        # fn(topic, detail) called AFTER a controller shed, outside the
        # broker lock (MatchService wires this to --annotate-rejects so
        # shed storms are debuggable from the journal). Must not call
        # back into the broker.
        self.shed_observer = None
        # exactly-once state (recovered from log stamps on reload)
        self._fence_epoch = 0
        self.fenced_produces = 0
        self.dup_suppressed = 0
        # latency attribution hook: fn(topic, records, now_us) called
        # after each non-empty fetch DELIVERS records to a consumer —
        # the serving process hosts the broker, so consumer receipt of
        # MatchOut is observable here (MatchService wires this to the
        # lat_consume histogram). Called outside the broker lock.
        self.deliver_observer = None
        if persist_dir is not None:
            os.makedirs(persist_dir, exist_ok=True)
            for name in sorted(os.listdir(persist_dir)):
                if name.endswith(".log"):
                    self._load_topic(name[:-4])

    # -- durability -----------------------------------------------------

    def _log_path(self, name: str) -> str:
        return os.path.join(self._persist_dir, f"{name}.log")

    def _load_topic(self, name: str) -> None:
        """Reload a topic log. Committed records are NEVER rewritten: a
        torn FINAL line (crash mid-append) is repaired crash-safely by
        truncating the file at the torn line's byte offset; an
        undecodable INTERIOR line is corruption of committed data and
        refuses to load (silently dropping everything after it would
        permanently lose records the checkpoint offset still addresses)."""
        path = self._log_path(name)
        topic = _Topic()
        with open(path, "rb") as f:
            data = f.read()
        pos = 0
        torn_at = None
        while pos < len(data):
            nl = data.find(b"\n", pos)
            if nl < 0:
                torn_at = pos  # unterminated trailing append
                break
            try:
                row = json.loads(data[pos:nl].decode("utf-8"))
                if len(row) not in (2, 4):
                    raise ValueError(f"bad row arity {len(row)}")
                key, value = row[0], row[1]
                epoch = row[2] if len(row) == 4 else None
                out_seq = row[3] if len(row) == 4 else None
            except (ValueError, TypeError, UnicodeDecodeError):
                # produce() appends each record as ONE newline-terminated
                # write, and partial writes are prefixes — so any line
                # that HAS its newline was committed whole; failing to
                # decode it means committed data corruption, not a crash
                # artifact, wherever it sits in the file.
                raise BrokerError(
                    f"corrupt record in {path} at byte {pos}: refusing "
                    f"to load (only an unterminated final line is "
                    f"repairable; committed records are immutable)")
            topic.log.append(Record(len(topic.log), key, value,
                                    epoch, out_seq))
            if out_seq is not None:
                topic.max_out_seq = max(topic.max_out_seq, int(out_seq))
            if epoch is not None:
                self._fence_epoch = max(self._fence_epoch, int(epoch))
            pos = nl + 1
        if torn_at is not None:
            print(f"broker: dropping torn tail of {path} at byte {torn_at} "
                  f"({len(data) - torn_at} bytes)", file=sys.stderr)
            with open(path, "r+b") as f:
                f.truncate(torn_at)
        topic.logfile = open(path, "a", encoding="utf-8")
        self._topics[name] = topic

    # -- admin ----------------------------------------------------------

    def create_topic(self, name: str, partitions: int = 1) -> bool:
        """Create a topic; False if it already exists (kafkajs
        createTopics semantics: returns false when nothing was created)."""
        if partitions != 1:
            raise BrokerError("only 1 partition per topic is supported "
                              "(the reference provisions exactly 1)")
        if "/" in name or name.startswith("."):
            raise BrokerError(f"invalid topic name {name!r}")
        with self._lock:
            if name in self._topics:
                return False
            logfile = None
            if self._persist_dir is not None:
                logfile = open(self._log_path(name), "a", encoding="utf-8")
            self._topics[name] = _Topic(partitions, logfile)
            return True

    def topics(self) -> Dict[str, int]:
        with self._lock:
            return {n: t.partitions for n, t in self._topics.items()}

    # -- data path ------------------------------------------------------

    def produce(self, topic: str, key: Optional[str], value: str,
                epoch: Optional[int] = None,
                out_seq: Optional[int] = None,
                ats: Optional[int] = None,
                tid: Optional[int] = None) -> int:
        """Append one record; returns its offset. With an
        ``(epoch, out_seq)`` stamp the append is fenced and idempotent:
        a stale epoch raises BrokerFenced, and an ``out_seq`` at or
        below the topic's durable watermark is suppressed (returns -1,
        nothing appended) — replayed tails after a crash vanish here
        instead of surfacing to consumers.

        ``ats`` overrides the admission stamp (microseconds): remote
        producers stamp at their FIRST send attempt and re-send the
        same stamp across reconnects, so latency histograms include the
        reconnect delay instead of hiding it (coordinated omission).

        ``tid`` attaches a transport-advisory trace word to the
        in-memory record (Record.tid); durable rows are unchanged."""
        if faults.should("broker.produce"):
            raise BrokerError("injected fault: broker.produce")
        with self._data:
            t = self._topics.get(topic)
            if t is None:
                raise BrokerError(f"unknown topic {topic!r}")
            if epoch is not None:
                if epoch < self._fence_epoch:
                    self.fenced_produces += 1
                    raise BrokerFenced(
                        f"fenced: produce to {topic!r} from stale epoch "
                        f"{epoch} < fence {self._fence_epoch}")
                self._fence_epoch = epoch
            if out_seq is not None and out_seq <= t.max_out_seq:
                self.dup_suppressed += 1
                return -1
            if (self._max_lag is not None and topic in self._commits
                    and len(t.log) - self._commits[topic]
                    >= self._max_lag):
                self.overload_rejects += 1
                raise BrokerOverload(
                    f"rej_overload: topic {topic!r} backlog "
                    f"{len(t.log) - self._commits[topic]} >= max_lag "
                    f"{self._max_lag}")
            shed_detail = None
            if self.overload is not None and topic in self._commits:
                ok, shed_detail = self.overload.admit(
                    value, len(t.log) - self._commits[topic])
                if not ok:
                    self.overload_rejects += 1
            if shed_detail is None:
                off = len(t.log)
                if ats is None:
                    ats = self._clock.time_us()
                t.log.append(Record(off, key, value, epoch, out_seq,
                                    ats, tid))
                if out_seq is not None:
                    t.max_out_seq = out_seq
                if topic in self._commits:
                    self.wire_json_records += 1
                if t.logfile is not None:
                    row = ([key, value]
                           if epoch is None and out_seq is None
                           else [key, value, epoch, out_seq])
                    t.logfile.write(json.dumps(row,
                                               separators=(",", ":"))
                                    + "\n")
                    t.logfile.flush()
                self._data.notify_all()
                return off
        # controller shed: annotate + raise OUTSIDE the broker lock (the
        # observer may touch journals/telemetry; it must never deadlock a
        # concurrent fetch)
        obs = self.shed_observer
        if obs is not None:
            try:
                obs(topic, shed_detail)
            except Exception:
                pass        # observability must never mask the shed
        exc = BrokerOverload(
            f"rej_overload: topic {topic!r} backlog "
            f"{shed_detail['backlog']} state {shed_detail['state']} "
            f"(adaptive shed, backoff {shed_detail['backoff_ms']} ms)")
        exc.backoff_ms = shed_detail["backoff_ms"]
        exc.detail = shed_detail
        raise exc

    def produce_frames(self, topic: str, key: Optional[str], buf: bytes,
                       epoch: Optional[int] = None,
                       seq0: Optional[int] = None,
                       ats: Optional[int] = None):
        """Binary batch append: one contiguous buffer of 72-byte wire
        frames (wire.py layout; 80 bytes when FLAG_TID carries a trace
        word) -> records, without materializing a Python dict per
        record. Trace words land on Record.tid only — the stored value
        bytes and durable rows are identical with tracing on or off. The frames decode ONCE (native
        kme_parse_frames + the pinned kme_parse_emit emitter when
        available) into the canonical order_json values the broker
        always stores — the durable log, oracle replay, and MatchOut
        bytes cannot tell which encoding carried a record. Admission
        control classifies straight off the decoded action column
        (classify_actions + admit_classified): no JSON anywhere on the
        path.

        Fencing/idempotence mirror produce(): with `epoch`/`seq0`,
        record i carries out_seq seq0+i and duplicates are suppressed
        individually. `ats` stamps the WHOLE batch (default: now).

        Returns (n_appended, last_offset). On a mid-batch refusal
        (max_lag or controller shed) the admitted prefix STAYS
        appended — identical to a producer looping produce() — and the
        raised BrokerOverload carries `.admitted` (records kept) plus
        the usual backoff hint, so binary producers resume from
        buf[admitted*72:] after backing off. Malformed frames raise
        wire.WireFrameError (rej_malformed class) with NOTHING
        appended — validation happens before admission."""
        if faults.should("broker.produce"):
            raise BrokerError("injected fault: broker.produce")
        import time as _time

        from kme_tpu import wire as _wire

        t0 = _time.perf_counter_ns()
        wb, values = _wire.frames_to_values(buf)
        cls_col = classify_actions(wb.action)
        oid_col, aid_col = wb.oid, wb.aid
        parse_ns = _time.perf_counter_ns() - t0
        if ats is None:
            ats = self._clock.time_us()
        appended, last_off = 0, -1
        shed_detail = overload_msg = None
        with self._data:
            self.wire_parse_ns += parse_ns
            t = self._topics.get(topic)
            if t is None:
                raise BrokerError(f"unknown topic {topic!r}")
            if epoch is not None:
                if epoch < self._fence_epoch:
                    self.fenced_produces += 1
                    raise BrokerFenced(
                        f"fenced: produce to {topic!r} from stale epoch "
                        f"{epoch} < fence {self._fence_epoch}")
                self._fence_epoch = epoch
            bounded = topic in self._commits
            lines: List[str] = []
            for i in range(wb.n):
                out_seq = None if seq0 is None else seq0 + i
                if out_seq is not None and out_seq <= t.max_out_seq:
                    self.dup_suppressed += 1
                    continue
                backlog = (len(t.log) - self._commits[topic]
                           if bounded else 0)
                if (self._max_lag is not None and bounded
                        and backlog >= self._max_lag):
                    self.overload_rejects += 1
                    overload_msg = (
                        f"rej_overload: topic {topic!r} backlog "
                        f"{backlog} >= max_lag {self._max_lag}")
                    break
                if self.overload is not None and bounded:
                    ok, shed_detail = self.overload.admit_classified(
                        int(cls_col[i]), int(oid_col[i]),
                        int(aid_col[i]), backlog)
                    if not ok:
                        self.overload_rejects += 1
                        break
                off = len(t.log)
                t.log.append(Record(off, key, values[i], epoch, out_seq,
                                    ats, wb.record_tid(i)))
                if out_seq is not None:
                    t.max_out_seq = out_seq
                if t.logfile is not None:
                    row = ([key, values[i]]
                           if epoch is None and out_seq is None
                           else [key, values[i], epoch, out_seq])
                    lines.append(json.dumps(row, separators=(",", ":"))
                                 + "\n")
                appended += 1
                last_off = off
            if lines:
                # ONE write + flush for the whole admitted prefix (the
                # per-record flush in produce() is the other half of
                # the JSON ingress tax). A torn tail still repairs:
                # partial writes are prefixes, so only the final line
                # can be incomplete — exactly what _load_topic fixes.
                _flush_log_lines(t.logfile, lines)
            if appended:
                self.wire_binary_records += appended
                self._data.notify_all()
        if overload_msg is None and shed_detail is None:
            return appended, last_off
        if shed_detail is not None:
            obs = self.shed_observer
            if obs is not None:
                try:
                    obs(topic, shed_detail)
                except Exception:
                    pass    # observability must never mask the shed
            exc = BrokerOverload(
                f"rej_overload: topic {topic!r} backlog "
                f"{shed_detail['backlog']} state {shed_detail['state']} "
                f"(adaptive shed, backoff {shed_detail['backoff_ms']} "
                f"ms)")
            exc.backoff_ms = shed_detail["backoff_ms"]
            exc.detail = shed_detail
        else:
            exc = BrokerOverload(overload_msg)
        exc.admitted = appended
        raise exc

    def fence(self, epoch: int) -> None:
        """Advance the fence so every produce stamped below `epoch` is
        rejected. A newly promoted leader calls this at startup: the
        reloaded log only teaches the broker its PREDECESSORS' epochs,
        so without an explicit fence a zombie old leader holding the
        previous epoch would still get through."""
        with self._lock:
            self._fence_epoch = max(self._fence_epoch, int(epoch))

    @property
    def fence_epoch(self) -> int:
        with self._lock:
            return self._fence_epoch

    def fetch(self, topic: str, offset: int, max_records: int = 1024,
              timeout: float = 0.0) -> List[Record]:
        """Records from `offset` (at most max_records). Blocks up to
        `timeout` seconds while the log end is <= offset."""
        if faults.should("broker.fetch"):
            raise BrokerError("injected fault: broker.fetch")
        with self._data:
            t = self._topics.get(topic)
            if t is None:
                raise BrokerError(f"unknown topic {topic!r}")
            if timeout > 0 and len(t.log) <= offset:
                self._data.wait_for(lambda: len(t.log) > offset,
                                    timeout=timeout)
            recs = t.log[offset:offset + max_records]
        obs = self.deliver_observer
        if obs is not None and recs:
            try:
                obs(topic, recs, self._clock.time_us())
            except Exception:
                pass        # observability must never fail a fetch
        return recs

    def commit(self, topic: str, offset: int) -> None:
        """Advance a consumer watermark (arms the `max_lag` ingress
        bound for `topic`). Monotonic; unknown topics raise."""
        with self._lock:
            if topic not in self._topics:
                raise BrokerError(f"unknown topic {topic!r}")
            cur = self._commits.get(topic, 0)
            self._commits[topic] = max(cur, int(offset))

    def end_offset(self, topic: str) -> int:
        with self._lock:
            t = self._topics.get(topic)
            if t is None:
                raise BrokerError(f"unknown topic {topic!r}")
            return len(t.log)

    def sync(self) -> None:
        """fsync every topic log to stable storage. `produce` only
        flush()es (process-crash durability); callers that are about to
        commit an offset DERIVED from these records (MatchService
        checkpoints) call sync() first so an fsync'd snapshot offset can
        never address records the OS lost in a power failure. The
        persist directory is fsync'd too: a freshly created topic log is
        a new directory entry, and POSIX only makes those durable after
        a directory fsync."""
        with self._lock:
            any_file = False
            for t in self._topics.values():
                if t.logfile is not None:
                    t.logfile.flush()
                    os.fsync(t.logfile.fileno())
                    any_file = True
            if any_file:
                dfd = os.open(self._persist_dir, os.O_RDONLY)
                try:
                    os.fsync(dfd)
                finally:
                    os.close(dfd)
