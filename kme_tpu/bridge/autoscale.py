"""Deterministic split/merge autoscaling policy (ROADMAP item 2c).

The SEDA lesson (Welsh et al., PAPERS.md) applied to topology instead
of admission: the `OverloadController` sheds load WITHIN a group; this
controller decides when the group count itself should change. It is a
pure state machine in the same mold — no wall clock, no RNG, no I/O —
consuming exactly the signals the serving side already exports:

- per-group input lag (the `group{k}_lag` heartbeat gauges),
- per-group overload state codes (`overload_state`: 0 normal,
  1 shedding, 2 draining — bridge/broker.py OverloadController),

and deriving `shard_imbalance` (max/mean lag) from them. Decisions are
doubling/halving proposals (N→2N split, N→N/2 merge) because the
rendezvous assignment moves the minimal key fraction for any target —
the move-cost the multihost bench gates — and a power-of-two ladder
keeps repeated decisions composable.

Hysteresis is explicit and threefold, so the policy cannot flap:
a split needs `dwell` CONSECUTIVE hot ticks (any group's lag at or
above `high_lag`, or any group shedding/draining); a merge needs
`dwell` consecutive cold ticks (EVERY group below `low_lag`, nobody
overloaded — and low_lag < high_lag is enforced, the watermark gap);
and any decision starts a `cooldown` tick window in which nothing new
is proposed (a reshard in flight must not be second-guessed by the
backlog spike it itself causes).

The controller PROPOSES; it never executes. `kme-supervise --groups
auto` feeds it from the group heartbeats and appends each decision to
<state_root>/autoscale.json, where an operator (or the chaos drill)
hands the proposal to `kme-reshard`. `simulate_autoscale` replays a
recorded gauge trace through a fresh controller — same trace, same
decisions, byte-for-byte, exactly like `simulate_overload`.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Sequence

SPLIT, MERGE = "split", "merge"


@dataclasses.dataclass(frozen=True)
class AutoscaleConfig:
    """Watermarks + hysteresis. Defaults pair with the serve-side
    OverloadController defaults: high_lag here matches its shedding
    watermark, so a split proposal lands before degradation does."""

    min_groups: int = 1
    max_groups: int = 8
    high_lag: float = 48.0      # any group at/above this is "hot"
    low_lag: float = 4.0        # every group below this is "cold"
    high_imbalance: float = 4.0  # max/mean lag that counts as hot
    dwell: int = 3              # consecutive ticks before a proposal
    cooldown: int = 8           # quiet ticks after any proposal

    def __post_init__(self) -> None:
        if self.min_groups < 1 or self.max_groups < self.min_groups:
            raise ValueError("need 1 <= min_groups <= max_groups")
        if not self.low_lag < self.high_lag:
            raise ValueError("need low_lag < high_lag (hysteresis gap)")
        if self.dwell < 1 or self.cooldown < 0:
            raise ValueError("need dwell >= 1 and cooldown >= 0")


def shard_imbalance(lags: Sequence[float]) -> float:
    """max/mean input lag across groups (1.0 = perfectly even; the
    PR 8 gauge this controller re-derives from per-group lags)."""
    if not lags:
        return 1.0
    mean = sum(lags) / len(lags)
    if mean <= 0:
        return 1.0
    return max(lags) / mean


class AutoscaleController:
    """observe() one tick -> an optional split/merge proposal dict.

    Every field of the proposal is a pure function of the observed
    tick sequence, so any consumer can re-derive (and audit) it by
    replay. Internal state is three small counters — the dwell streaks
    and the cooldown — which is the whole memory of the policy."""

    def __init__(self, cfg: Optional[AutoscaleConfig] = None) -> None:
        self.cfg = cfg or AutoscaleConfig()
        self.hot_streak = 0
        self.cold_streak = 0
        self.cooldown_left = 0
        self.ticks = 0
        self.decisions: List[dict] = []

    def observe(self, groups: int, lags: Sequence[float],
                overload_states: Sequence[int] = (),
                tick: Optional[int] = None) -> Optional[dict]:
        """One control tick: current group count, per-group input lags,
        per-group overload state codes. Returns the proposal dict (also
        appended to self.decisions) or None."""
        cfg = self.cfg
        self.ticks += 1
        t = self.ticks if tick is None else int(tick)
        lags = [float(x) for x in lags]
        overloaded = any(int(s) > 0 for s in overload_states)
        imb = shard_imbalance(lags)
        hot = (overloaded
               or (bool(lags) and max(lags) >= cfg.high_lag)
               or (len(lags) > 1 and imb >= cfg.high_imbalance
                   and max(lags) > cfg.low_lag))
        cold = (not overloaded
                and (not lags or max(lags) < cfg.low_lag))
        self.hot_streak = self.hot_streak + 1 if hot else 0
        self.cold_streak = self.cold_streak + 1 if cold else 0
        if self.cooldown_left > 0:
            self.cooldown_left -= 1
            return None
        action = to = None
        if self.hot_streak >= cfg.dwell and groups < cfg.max_groups:
            action, to = SPLIT, min(cfg.max_groups, groups * 2)
        elif self.cold_streak >= cfg.dwell and groups > cfg.min_groups:
            action, to = MERGE, max(cfg.min_groups, groups // 2)
        if action is None:
            return None
        decision = {"tick": t, "action": action, "from": int(groups),
                    "to": int(to), "max_lag": max(lags) if lags else 0.0,
                    "imbalance": round(imb, 4),
                    "overloaded": overloaded,
                    "streak": (self.hot_streak if action == SPLIT
                               else self.cold_streak)}
        self.decisions.append(decision)
        self.hot_streak = self.cold_streak = 0
        self.cooldown_left = cfg.cooldown
        return decision


def simulate_autoscale(samples: Sequence[dict],
                       cfg: Optional[AutoscaleConfig] = None) -> dict:
    """Replay a recorded gauge trace through a fresh controller —
    the simulate_overload twin. Each sample:
    {"groups": N, "lags": [...], "overload": [...], "tick": t?}.
    Group count FOLLOWS proposals during the replay (a split's effect
    on subsequent ticks' `groups` input is part of the policy being
    audited) unless the sample pins "groups" explicitly."""
    ctl = AutoscaleController(cfg)
    groups: Optional[int] = None
    for s in samples:
        if s.get("groups") is not None:
            groups = int(s["groups"])
        elif groups is None:
            raise ValueError("first sample must carry 'groups'")
        d = ctl.observe(groups, s.get("lags", ()),
                        s.get("overload", ()), tick=s.get("tick"))
        if d is not None:
            groups = d["to"]
    return {"ticks": ctl.ticks, "decisions": list(ctl.decisions),
            "final_groups": groups}


def load_trace(path: str) -> List[dict]:
    """Read a JSONL gauge trace (one sample per line) for replay."""
    out = []
    with open(path, encoding="utf-8") as f:
        for ln in f:
            ln = ln.strip()
            if ln:
                out.append(json.loads(ln))
    return out


def tick_event(ctl: AutoscaleController, groups: int,
               lags: Sequence[float],
               overload_states: Sequence[int],
               decision: Optional[dict]) -> dict:
    """The flight-recorder payload for one policy tick, read AFTER
    ``observe``: which hysteresis phase the controller is in
    (hot/cold dwell, post-proposal cooldown, steady), the raw inputs
    it saw, and — when this tick crossed the dwell threshold — the
    proposal itself. Pure function of controller state: the emitting
    monitor does the I/O, the policy stays byte-replayable."""
    if decision is not None:
        phase = "propose"
    elif ctl.cooldown_left > 0:
        phase = "cooldown"
    elif ctl.hot_streak > 0:
        phase = "hot-dwell"
    elif ctl.cold_streak > 0:
        phase = "cold-dwell"
    else:
        phase = "steady"
    detail = {"phase": phase, "groups": int(groups),
              "tick": ctl.ticks,
              "max_lag": round(max(lags), 3) if lags else 0.0,
              "overloaded": int(sum(1 for s in overload_states if s)),
              "hot_streak": ctl.hot_streak,
              "cold_streak": ctl.cold_streak,
              "cooldown_left": ctl.cooldown_left}
    if decision is not None:
        detail.update(action=decision["action"],
                      to=int(decision["to"]),
                      imbalance=decision.get("imbalance"))
    return detail
