"""kme-standby: hot-standby replica with bounded-failover promotion.

The reference gets warm spares from Kafka Streams standby replicas
(num.standby.replicas — state restored from changelogs on another
instance, promoted by the group coordinator when the active dies). Here
the same role is a second process sharing the leader's durable state
root read-only:

- it restores the NEWEST snapshot at startup (the ordinary resume path)
  and then TAILS the leader's durable MatchIn topic log
  (<checkpoint-dir>/broker-log/MatchIn.log) through _FollowBroker,
  applying input through the same MatchService the leader runs — so its
  engine state stays within one batch of the leader's;
- application is BOUNDED by the leader's heartbeat offset
  (serve.health) MINUS one batch: output the follower generates is
  discarded but still COUNTED into the (epoch, out_seq) produce-stamp
  cursor, and counting output the leader never confirmed would
  desynchronize that cursor from the durable MatchOut log. The one-
  batch holdback is deliberate: it keeps the follower's cursor STRICTLY
  BEHIND the leader's durable output, so every promotion re-produces at
  least the last confirmed batch — stamps the broker's idempotent-
  produce watermark suppresses. Broker-side dedup is therefore
  exercised on every real failover (dup_suppressed_total > 0 is an
  invariant the chaos drill asserts, not a race), at the cost of
  replaying at most one batch at promotion time;
- when the supervisor detects leader death and the standby looks ready,
  it writes <checkpoint-dir>/promote.json; the follower notices within
  one poll, acquires the NEXT leader epoch, fences every predecessor at
  the broker, reopens the durable topic logs as a real broker, binds
  the leader's TCP endpoint and keeps serving from its applied offset.
  The overlap between its applied offset and whatever the dead leader
  already produced replays through the broker's idempotent-produce
  watermark, which suppresses the duplicate stamps — the visible
  MatchOut stream stays exactly-once across the failover.

The old leader, should it still be alive (a stall, not a death), is
FENCED: its next stamped produce carries a stale epoch and the broker
rejects it (BrokerFenced -> kme-serve exits 75 -> its supervisor gives
it a fresh epoch — but by then this replica owns the stream).
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import sys
from typing import List, Optional

from kme_tpu import faults
from kme_tpu.bridge.broker import (BrokerError, BrokerFenced,
                                   InProcessBroker, Record)
from kme_tpu.bridge.service import TOPIC_IN, MatchService

PROMOTE_FILE = "promote.json"


class _FollowBroker:
    """Read-only broker facade over the leader's durable MatchIn log.

    fetch() serves records parsed straight from the append-only JSONL
    file, never past `limit` (the leader's last heartbeat offset — see
    the module docstring for why running ahead is unsafe). produce() is
    a counting discard: MatchService's follower mode only needs the
    call to succeed so its out_seq cursor advances. A torn tail (the
    leader died mid-append) is left unconsumed and re-read on the next
    poll; a file that SHRANK (a fresh run reusing the directory) resets
    the tail cursor entirely.
    """

    def __init__(self, log_dir: str, topic: str = TOPIC_IN,
                 clock=None) -> None:
        from kme_tpu.bridge.clock import WALL

        self._clock = clock or WALL
        self._path = os.path.join(log_dir, f"{topic}.log")
        self._topic = topic
        self._recs: List[Record] = []
        self._pos = 0           # bytes of fully-parsed log lines
        self.limit = 0          # leader-confirmed applied offset bound
        self.discarded = 0      # produces swallowed while following

    def _poll(self) -> None:
        try:
            with open(self._path, "rb") as f:
                f.seek(self._pos)
                data = f.read()
        except OSError:
            return              # leader has not created the topic yet
        if not data:
            with contextlib.suppress(OSError):
                if os.path.getsize(self._path) < self._pos:
                    self._recs, self._pos = [], 0   # truncated: re-read
            return
        consumed = 0
        while True:
            nl = data.find(b"\n", consumed)
            if nl < 0:
                break           # torn tail: retry once it completes
            try:
                row = json.loads(data[consumed:nl].decode("utf-8"))
                if not isinstance(row, list) or len(row) not in (2, 4):
                    raise ValueError("bad log row arity")
            except (ValueError, UnicodeDecodeError):
                break           # torn mid-file line: stop, re-read later
            consumed = nl + 1
            self._recs.append(Record(
                len(self._recs), row[0], row[1],
                row[2] if len(row) > 2 else None,
                row[3] if len(row) > 3 else None))
        self._pos += consumed

    def fetch(self, topic: str, offset: int, max_records: int,
              timeout: float = 0.0) -> List[Record]:
        if topic != self._topic:
            raise BrokerError(f"unknown topic {topic!r}")
        self._poll()
        end = min(len(self._recs), self.limit, offset + max_records)
        recs = self._recs[offset:end]
        if not recs and timeout > 0:
            self._clock.sleep(min(timeout, 0.1))
        return recs

    def end_offset(self, topic: str) -> int:
        self._poll()
        return len(self._recs)

    def produce(self, topic: str, key, value) -> int:
        self.discarded += 1
        return -1


class Replica:
    """The follow -> promote state machine around one MatchService."""

    def __init__(self, checkpoint_dir: str,
                 listen: str = "127.0.0.1:9092",
                 engine: str = "seq", compat: str = "fixed",
                 batch: int = 1024, symbols: int = 1024,
                 accounts: int = 4096, slots: int = 128,
                 max_fills: int = 16, width: int = 8, shards: int = 1,
                 checkpoint_every: int = 4096,
                 checkpoint_keep: Optional[int] = None,
                 max_lag: Optional[int] = None,
                 promote_file: Optional[str] = None,
                 health_file: Optional[str] = None,
                 serve_health: Optional[str] = None,
                 poll: float = 0.2, health_every: float = 1.0,
                 max_messages: Optional[int] = None,
                 idle_exit: Optional[float] = None,
                 metrics_port: Optional[int] = None,
                 group=None, journal_out: Optional[str] = None,
                 trace_spans: bool = False,
                 tsdb: Optional[str] = None, clock=None) -> None:
        from kme_tpu.bridge.clock import WALL

        # the clock seam (bridge/clock.py): the follow loop's poll
        # cadence, heartbeat gating and promotion deadline all run off
        # this object so a simulated standby never blocks real time
        self.clock = clock or WALL
        self.group = group
        # armed at PROMOTION only: a follower's output is discarded, so
        # journaling its stages would double-record every offset the
        # leader already covered — the promoted leader resumes the
        # leader's journal (resume=True) and continues the same
        # per-order span stream (a gap during the outage, not a fork)
        self.journal_out = journal_out
        self.trace_spans = trace_spans
        self.checkpoint_dir = checkpoint_dir
        self.listen = listen
        self.max_lag = max_lag
        self.poll = poll
        self.health_every = health_every
        self.health_file = health_file
        self.max_messages = max_messages
        self.idle_exit = idle_exit
        self.promote_file = promote_file or os.path.join(
            checkpoint_dir, PROMOTE_FILE)
        self.serve_health = serve_health or os.path.join(
            checkpoint_dir, "serve.health")
        self.log_dir = os.path.join(checkpoint_dir, "broker-log")
        self.holdback = max(1, batch)   # stay one batch behind (docstring)
        self._ppid = os.getppid()   # orphan detection (follow loop)
        topic_in = TOPIC_IN
        if group is not None and group[1] > 1:
            # shard-group mode: follow the group's namespaced input log
            topic_in = f"{TOPIC_IN}.g{group[0]}"
        self.follow = _FollowBroker(self.log_dir, topic=topic_in,
                                    clock=self.clock)
        self.svc = MatchService(
            self.follow, engine=engine, compat=compat, batch=batch,
            symbols=symbols, accounts=accounts, slots=slots,
            max_fills=max_fills, width=width, shards=shards,
            checkpoint_dir=checkpoint_dir,
            checkpoint_every=checkpoint_every,
            checkpoint_keep=checkpoint_keep,
            exactly_once=True, follower=True, group=group,
            clock=self.clock)
        self.tsdb = None
        self._tsdb_dir = tsdb
        if tsdb is not None:
            # the standby writes its own per-source history next to the
            # leader's in the shared TSDB dir; no checkpoint carries a
            # follower's sample cursor, so it adopts the store's
            # next_seq (replays after a standby restart would otherwise
            # dedup against its own history forever)
            from kme_tpu.telemetry import TSDB
            source = "standby"
            if group is not None and group[1] > 1:
                source = f"standby.g{group[0]}"
            try:
                self.tsdb = TSDB(tsdb, source=source)
                self._tsdb_seq = self.tsdb.next_seq()
            except (OSError, ValueError) as e:
                print(f"kme-standby: TSDB disabled: {e}",
                      file=sys.stderr)
        self.metrics_server = None
        if metrics_port is not None:
            # the standby's own metrics surface (kme-top scrapes it
            # next to the leader's to show replica lag live)
            from kme_tpu.telemetry import start_metrics_server

            self.metrics_server = start_metrics_server(
                self.svc.telemetry, metrics_port)
            print(f"kme-standby: metrics on http://"
                  f"{self.metrics_server.server_address[0]}:"
                  f"{self.metrics_server.server_address[1]}/metrics",
                  file=sys.stderr)

    # -- following ------------------------------------------------------

    def _read_promote(self) -> Optional[dict]:
        """The promotion order — only if addressed to THIS process (a
        replacement standby spawned behind a promotion must never act
        on, or delete, the adoptee's order). pid-less promote files are
        honored for manual/test-driven promotion."""
        try:
            with open(self.promote_file) as f:
                data = json.load(f)
        except (OSError, ValueError):
            return None
        pid = data.get("pid")
        if pid is not None and pid != os.getpid():
            return None
        return data

    def _leader_offset(self) -> int:
        """The leader's last confirmed applied offset — the follower
        must never apply input beyond it (module docstring)."""
        try:
            with open(self.serve_health) as f:
                hb = json.load(f)
            if hb.get("role") == "leader":
                return int(hb.get("offset", 0))
        except (OSError, ValueError, TypeError):
            pass
        return 0

    def _write_heartbeat(self, applied: int, tick: int) -> None:
        snap = self.svc.telemetry.snapshot()
        if self.tsdb is not None:
            try:
                seq = self._tsdb_seq
                self._tsdb_seq = seq + 1
                self.tsdb.append_snapshot(snap, seq)
            except OSError:
                self.tsdb = None    # history is best-effort
        if self.health_file is None:
            return
        tmp = self.health_file + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump({"pid": os.getpid(),
                           "time": self.clock.time(),
                           "role": "standby", "applied": applied,
                           "tick": tick,
                           "out_seq": self.svc.out_seq,
                           "discarded": self.follow.discarded,
                           "leader_offset": self._leader_offset(),
                           "metrics": snap}, f)
            os.replace(tmp, self.health_file)
        except OSError:
            pass        # reporting surface only

    def run(self) -> int:
        svc = self.svc
        print(f"kme-standby: following {self.log_dir} from offset "
              f"{svc.offset} (out_seq {svc.out_seq})", file=sys.stderr)
        tick = 0
        last_hb = 0.0
        while True:
            promote = self._read_promote()
            if promote is not None:
                return self._promote(promote)
            if os.getppid() != self._ppid:
                # reparented: the supervisor that would ever promote us
                # is gone — a follower with no path to leadership is an
                # orphan, not a service
                print("kme-standby: supervisor died; exiting",
                      file=sys.stderr)
                return 0
            self.follow.limit = max(self.follow.limit,
                                    self._leader_offset() - self.holdback)
            n = svc.step(timeout=self.poll)
            tick += 1
            if n and faults.should("standby.lag", offset=svc.offset):
                print(f"kme-faults: standby stalled at offset "
                      f"{svc.offset}", file=sys.stderr)
                self.clock.sleep(1.0)
            now = self.clock.monotonic()
            if now - last_hb >= self.health_every:
                last_hb = now
                lead = self._leader_offset()
                t = svc.telemetry
                t.gauge("replica_applied_offset").set(svc.offset)
                t.gauge("replica_leader_offset").set(lead)
                t.gauge("replica_lag_records",
                        "input records the leader confirmed but this "
                        "standby has not applied").set(
                    max(0, lead - svc.offset))
                self._write_heartbeat(svc.offset, tick)

    # -- promotion ------------------------------------------------------

    def _promote(self, promote: dict) -> int:
        """Become the leader: next epoch, real broker over the durable
        logs, the leader's TCP endpoint, and the ordinary serve loop.
        The applied-offset .. dead-leader-output overlap replays through
        the broker's idempotent-produce watermark (see module
        docstring)."""
        from kme_tpu.bridge.provision import group_topics, provision
        from kme_tpu.bridge.tcp import parse_addr, serve_broker

        svc = self.svc
        # flight recorder: promotion begin/end bracket the whole
        # takeover (broker reopen, endpoint rebind, epoch fence) so the
        # merged timeline shows the failover window, not just its end.
        # The standby's own source name keeps it distinct from the
        # supervisor's promote decision in the merged view.
        from kme_tpu.telemetry import events as cpevents

        evlog = cpevents.open_log(self.checkpoint_dir, "standby",
                                  clock=self.clock.time)
        try:
            evlog.emit("replica.promote.begin",
                       group=(self.group[0] if self.group else None),
                       offset=svc.offset,
                       failed_at=promote.get("failed_at"))
        except Exception:
            pass
        if self.tsdb is not None:
            # hand history over to the serve path: the promoted leader
            # continues the LEADER's source series (adopting its
            # next_seq cursor from disk), not the standby's
            self.tsdb.close()
            self.tsdb = None
            svc._tsdb_arg = self._tsdb_dir
            svc.follower = False    # source name resolves to "serve"
            svc._init_profiling(resumed=False)
        with contextlib.suppress(OSError):
            os.unlink(self.promote_file)
        broker = InProcessBroker(persist_dir=self.log_dir,
                                 max_lag=self.max_lag)
        provision(broker, topics=(group_topics(self.group[0])
                                  if self.group is not None
                                  and self.group[1] > 1 else None))
        # ^ idempotent; logs already reloaded
        host, port = parse_addr(self.listen)
        deadline = self.clock.monotonic() + 10.0
        while True:
            try:
                # the dead leader's socket may linger in TIME_WAIT for
                # a moment even with SO_REUSEADDR; retry briefly
                srv, broker = serve_broker(host, port, broker)
                break
            except OSError:
                if self.clock.monotonic() >= deadline:
                    raise
                self.clock.sleep(0.1)
        svc.broker = broker
        svc.follower = False
        svc._init_exactly_once(resumed=False)   # next epoch + fence
        if self.journal_out is not None and svc.journal is None:
            # resume the dead leader's journal so the per-order span
            # stream CONTINUES across the failover (rewound to our
            # applied offset exactly like the serve resume path — the
            # overlap we re-process re-journals, and the stitcher
            # dedups it by (group, local_off, kind))
            from kme_tpu.telemetry import Journal

            svc.journal = Journal(self.journal_out)
            svc.journal.rewind_to_offset(svc.offset)
            svc.trace_spans = bool(self.trace_spans)
        failover = None
        try:
            failed_at = float(promote["failed_at"])
            failover = round(max(0.0, self.clock.time() - failed_at), 3)
            svc.telemetry.gauge("failover_seconds").set(failover)
        except (KeyError, TypeError, ValueError):
            pass
        print(f"kme-standby: PROMOTED to leader epoch {svc.epoch} at "
              f"offset {svc.offset} (out_seq {svc.out_seq}, "
              f"failover {failover if failover is not None else '?'}s)",
              file=sys.stderr)
        try:
            evlog.emit("replica.promote.end",
                       group=(self.group[0] if self.group else None),
                       epoch=svc.epoch, offset=svc.offset,
                       out_seq=svc.out_seq,
                       failover_seconds=failover)
            evlog.close()
        except Exception:
            pass
        try:
            seen = svc.run(max_messages=self.max_messages,
                           idle_exit=self.idle_exit,
                           health_file=self.serve_health,
                           health_every=self.health_every)
            svc.checkpoint()
            print(f"kme-standby: processed {seen} records as leader",
                  file=sys.stderr)
            return 0
        finally:
            svc.close()
            srv.shutdown()
            if hasattr(broker, "close"):
                broker.close()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="kme-standby", description=__doc__,
                                formatter_class=argparse.
                                RawDescriptionHelpFormatter)
    p.add_argument("--checkpoint-dir", required=True,
                   help="the LEADER's state root (snapshots, broker "
                        "logs, lease, promote file) — shared read-only "
                        "until promotion")
    p.add_argument("--listen", default="127.0.0.1:9092",
                   metavar="HOST:PORT",
                   help="the leader's broker endpoint, bound at "
                        "promotion")
    p.add_argument("--engine", choices=("seq", "lanes", "oracle",
                                        "native"), default="seq")
    p.add_argument("--compat", choices=("java", "fixed"),
                   default="fixed")
    p.add_argument("--batch", type=int, default=1024)
    p.add_argument("--symbols", type=int, default=1024)
    p.add_argument("--accounts", type=int, default=4096)
    p.add_argument("--slots", type=int, default=128)
    p.add_argument("--max-fills", type=int, default=16)
    p.add_argument("--width", type=int, default=8)
    p.add_argument("--shards", type=int, default=1)
    p.add_argument("--checkpoint-every", type=int, default=4096)
    p.add_argument("--checkpoint-keep", type=int, default=None)
    p.add_argument("--max-lag", type=int, default=None)
    p.add_argument("--idle-exit", type=float, default=None,
                   help="applies AFTER promotion (a follower waits "
                        "indefinitely)")
    p.add_argument("--max-messages", type=int, default=None)
    p.add_argument("--health-file", default=None, metavar="PATH",
                   help="standby heartbeat JSON ({pid, time, role, "
                        "applied, tick}); the supervisor requires it "
                        "before promoting")
    p.add_argument("--health-every", type=float, default=1.0)
    p.add_argument("--promote-file", default=None, metavar="PATH",
                   help="promotion trigger written by kme-supervise "
                        "(default <checkpoint-dir>/promote.json)")
    p.add_argument("--serve-health-file", default=None, metavar="PATH",
                   help="the LEADER's heartbeat to bound application "
                        "by (default <checkpoint-dir>/serve.health); "
                        "reused as this process's own heartbeat after "
                        "promotion")
    p.add_argument("--poll", type=float, default=0.2,
                   help="follow-loop poll interval (also the promote-"
                        "file detection latency bound)")
    p.add_argument("--metrics-port", type=int, default=None,
                   metavar="PORT",
                   help="serve this standby's own /metrics + "
                        "/metrics.json (0 picks a free port); kme-top "
                        "scrapes it next to the leader's")
    p.add_argument("--group", default=None, metavar="K/N",
                   help="follow shard group K of N (namespaced "
                        "MatchIn.gK log; promotion rebinds the group's "
                        "own topics)")
    p.add_argument("--journal-out", default=None, metavar="PATH",
                   help="armed at PROMOTION: resume the dead leader's "
                        "journal at this path and keep recording "
                        "(same spelling as kme-serve, so forwarded "
                        "serve_args just work)")
    p.add_argument("--trace-spans", action="store_true",
                   help="armed at PROMOTION: continue the leader's "
                        "per-order span stream (requires "
                        "--journal-out)")
    p.add_argument("--tsdb", default=None, metavar="DIR",
                   help="append this standby's heartbeat metrics to "
                        "the shared on-disk time-series store (source "
                        "'standby'); at promotion the store is handed "
                        "to the serve path and history continues under "
                        "the leader's source")
    args, unknown = p.parse_known_args(argv)
    if unknown:
        # the supervisor forwards the leader's serve_args verbatim;
        # serve-only flags (journal, metrics, strict, ...) don't apply
        # to a follower and are ignored loudly rather than fatally
        print(f"kme-standby: ignoring serve-only flag(s): "
              f"{' '.join(unknown)}", file=sys.stderr)
    group = None
    if args.group is not None:
        try:
            gk, gn = (int(x) for x in args.group.split("/", 1))
        except ValueError:
            print(f"kme-standby: --group wants K/N, got {args.group!r}",
                  file=sys.stderr)
            return 2
        group = (gk, gn)
    rep = Replica(args.checkpoint_dir, listen=args.listen,
                  engine=args.engine, compat=args.compat,
                  batch=args.batch, symbols=args.symbols,
                  accounts=args.accounts, slots=args.slots,
                  max_fills=args.max_fills, width=args.width,
                  shards=args.shards,
                  checkpoint_every=args.checkpoint_every,
                  checkpoint_keep=args.checkpoint_keep,
                  max_lag=args.max_lag,
                  promote_file=args.promote_file,
                  health_file=args.health_file,
                  serve_health=args.serve_health_file,
                  poll=args.poll, health_every=args.health_every,
                  max_messages=args.max_messages,
                  idle_exit=args.idle_exit,
                  metrics_port=args.metrics_port,
                  group=group, journal_out=args.journal_out,
                  trace_spans=args.trace_spans, tsdb=args.tsdb)
    try:
        return rep.run()
    except BrokerFenced as e:
        print(f"kme-standby: FENCED: {e}", file=sys.stderr)
        return 75
    except KeyboardInterrupt:
        return 0
    finally:
        if rep.tsdb is not None:
            rep.tsdb.close()
        if rep.metrics_server is not None:
            rep.metrics_server.shutdown()


if __name__ == "__main__":
    sys.exit(main())
