"""Fill-stream consumer — the consumer.js role
(/root/reference/consumer.js:10-20): subscribe to `MatchOut` from the
beginning and print one `<key> <value>` line per record."""

from __future__ import annotations

import argparse
import sys

from kme_tpu.bridge.service import TOPIC_OUT


def consume_lines(broker, offset: int = 0, follow: bool = True,
                  poll_timeout: float = 0.5, idle_exit: float = None):
    """Yield `<key> <value>` lines from MatchOut starting at `offset`.
    follow=False stops at the current end; idle_exit stops after that
    many idle seconds. While following, a missing topic is polled for
    (subscribe-and-wait, like the reference consumer and
    MatchService.step) instead of crashing a consumer that was started
    before provisioning."""
    import time

    from kme_tpu.bridge.broker import BrokerError

    idle_since = time.monotonic()
    while True:
        try:
            recs = broker.fetch(TOPIC_OUT, offset, 4096,
                                timeout=poll_timeout if follow else 0.0)
        except BrokerError as e:
            # only a not-yet-provisioned topic is waited for; anything
            # else (dead broker, protocol error) stays fatal so a
            # follower doesn't silently busy-loop on a lost broker
            if not follow or "unknown topic" not in str(e):
                raise
            if (idle_exit is not None
                    and time.monotonic() - idle_since >= idle_exit):
                return
            time.sleep(min(poll_timeout, 0.05))
            continue
        if not recs:
            if not follow:
                return
            if (idle_exit is not None
                    and time.monotonic() - idle_since >= idle_exit):
                return
            continue
        idle_since = time.monotonic()
        for r in recs:
            yield f"{r.key} {r.value}"
        offset = recs[-1].offset + 1


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="kme-consume", description=__doc__)
    p.add_argument("--broker", default="127.0.0.1:9092", metavar="HOST:PORT")
    p.add_argument("--no-follow", action="store_true",
                   help="stop at the current end of MatchOut")
    p.add_argument("--idle-exit", type=float, default=None, metavar="SECS",
                   help="exit after this many seconds with no new records")
    args = p.parse_args(argv)
    from kme_tpu.bridge.tcp import TcpBroker, parse_addr

    host, port = parse_addr(args.broker)
    client = TcpBroker(host, port)
    try:
        for line in consume_lines(client, follow=not args.no_follow,
                                  idle_exit=args.idle_exit):
            print(line, flush=True)
    except KeyboardInterrupt:
        pass
    finally:
        client.close()
    return 0
