"""Fill-stream consumer — the consumer.js role
(/root/reference/consumer.js:10-20): subscribe to `MatchOut` from the
beginning and print one `<key> <value>` line per record.

Under the exactly-once output path every MatchOut record carries an
`(epoch, out_seq)` produce stamp (wire.ProduceStamp) and the broker
already suppresses replayed stamps before they reach the log; the
DedupRing here is the consumer's defense-in-depth for streams that
bypassed broker dedup (a log written before fencing was enabled, or a
transport without stamp support) — it drops any stamp it has already
seen and counts the drop in `dup_suppressed_total`."""

from __future__ import annotations

import argparse
import collections
import sys

from kme_tpu.bridge.service import TOPIC_OUT


class DedupRing:
    """Ring of the most recent `capacity` (epoch, out_seq) produce
    stamps. Replay after a crash is CONTIGUOUS (the post-snapshot tail),
    so a ring bounded well above the checkpoint interval catches every
    real duplicate without unbounded memory; unstamped records pass
    through untouched."""

    def __init__(self, capacity: int = 65536) -> None:
        self.capacity = max(1, int(capacity))
        self._order = collections.deque()
        self._seen = set()
        self.suppressed = 0

    def is_dup(self, epoch, out_seq) -> bool:
        """True (and counted) when this stamp was already seen."""
        if epoch is None or out_seq is None:
            return False
        stamp = (epoch, out_seq)
        if stamp in self._seen:
            self.suppressed += 1
            return True
        self._seen.add(stamp)
        self._order.append(stamp)
        if len(self._order) > self.capacity:
            self._seen.discard(self._order.popleft())
        return False


def consume_lines(broker, offset: int = 0, follow: bool = True,
                  poll_timeout: float = 0.5, idle_exit: float = None,
                  dedup: DedupRing = None, latency=None):
    """Yield `<key> <value>` lines from MatchOut starting at `offset`.
    follow=False stops at the current end; idle_exit stops after that
    many idle seconds. While following, a missing topic is polled for
    (subscribe-and-wait, like the reference consumer and
    MatchService.step) instead of crashing a consumer that was started
    before provisioning. `dedup` suppresses records whose produce stamp
    the ring has already seen.

    `latency` (a telemetry LatencyHistogram, or any object with
    observe(seconds)) receives the receipt latency — now minus the
    record's broker-admission stamp `ats` — for every delivered record
    that carries one. This measures from intended start (produce
    admission), not from this consumer's dequeue, so a stalled consumer
    shows its backlog as latency instead of hiding it."""
    import time

    from kme_tpu.bridge.broker import BrokerError

    idle_since = time.monotonic()
    while True:
        try:
            recs = broker.fetch(TOPIC_OUT, offset, 4096,
                                timeout=poll_timeout if follow else 0.0)
        except BrokerError as e:
            # only a not-yet-provisioned topic is waited for; anything
            # else (dead broker, protocol error) stays fatal so a
            # follower doesn't silently busy-loop on a lost broker
            if not follow or "unknown topic" not in str(e):
                raise
            if (idle_exit is not None
                    and time.monotonic() - idle_since >= idle_exit):
                return
            time.sleep(min(poll_timeout, 0.05))
            continue
        if not recs:
            if not follow:
                return
            if (idle_exit is not None
                    and time.monotonic() - idle_since >= idle_exit):
                return
            continue
        idle_since = time.monotonic()
        now_us = time.time_ns() // 1000
        for r in recs:
            if dedup is not None and dedup.is_dup(
                    getattr(r, "epoch", None), getattr(r, "out_seq", None)):
                continue
            ats = getattr(r, "ats", None)
            if latency is not None and ats is not None:
                latency.observe(max(0, now_us - ats) * 1e-6)
            yield f"{r.key} {r.value}"
        offset = recs[-1].offset + 1


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="kme-consume", description=__doc__)
    p.add_argument("--broker", default="127.0.0.1:9092", metavar="HOST:PORT")
    p.add_argument("--no-follow", action="store_true",
                   help="stop at the current end of MatchOut")
    p.add_argument("--idle-exit", type=float, default=None, metavar="SECS",
                   help="exit after this many seconds with no new records")
    p.add_argument("--no-dedup", action="store_true",
                   help="print replayed stamped records too (raw "
                        "at-least-once view of the log)")
    p.add_argument("--latency", action="store_true",
                   help="print a receipt-latency summary (produce "
                        "admission -> consumer delivery) on exit")
    p.add_argument("--tsdb-out", default=None, metavar="DIR",
                   help="append delivery counters (and, with "
                        "--latency, receipt-latency quantiles) to the "
                        "shared on-disk time-series store every second "
                        "(source 'consume'; kme-prof queries it)")
    args = p.parse_args(argv)
    import time

    from kme_tpu.bridge.tcp import TcpBroker, parse_addr
    from kme_tpu.telemetry import LatencyHistogram

    host, port = parse_addr(args.broker)
    client = TcpBroker(host, port)
    ring = None if args.no_dedup else DedupRing()
    lat = LatencyHistogram("consume_receipt") if args.latency else None
    tsdb = None
    tsdb_seq = 0
    if args.tsdb_out is not None:
        from kme_tpu.telemetry import TSDB

        try:
            tsdb = TSDB(args.tsdb_out, source="consume")
            tsdb_seq = tsdb.next_seq()  # no durable cursor: adopt disk
        except (OSError, ValueError) as e:
            print(f"kme-consume: TSDB disabled: {e}", file=sys.stderr)
    delivered = 0
    last_sample = time.monotonic()

    def _tsdb_sample():
        nonlocal tsdb, tsdb_seq
        if tsdb is None:
            return
        vals = {"consume_delivered_total": delivered,
                "consume_dup_suppressed_total":
                    ring.suppressed if ring is not None else 0}
        if lat is not None and lat.count:
            qs = lat.quantiles()
            vals["consume_receipt.count"] = lat.count
            vals["consume_receipt.p50_ms"] = qs[0.5] * 1e3
            vals["consume_receipt.p99_ms"] = qs[0.99] * 1e3
            vals["consume_receipt.p999_ms"] = qs[0.999] * 1e3
        try:
            tsdb.append_values(vals, tsdb_seq)
            tsdb_seq += 1
        except OSError:
            tsdb = None         # history is best-effort
    try:
        for line in consume_lines(client, follow=not args.no_follow,
                                  idle_exit=args.idle_exit, dedup=ring,
                                  latency=lat):
            print(line, flush=True)
            delivered += 1
            now = time.monotonic()
            if tsdb is not None and now - last_sample >= 1.0:
                last_sample = now
                _tsdb_sample()
    except KeyboardInterrupt:
        pass
    finally:
        if delivered or lat is not None:
            _tsdb_sample()      # final cumulative sample
        if tsdb is not None:
            tsdb.close()
        client.close()
        if ring is not None and ring.suppressed:
            print(f"kme-consume: suppressed {ring.suppressed} duplicate "
                  f"record(s)", file=sys.stderr)
        if lat is not None and lat.count:
            qs = lat.quantiles()
            print("kme-consume: receipt latency "
                  f"n={lat.count} "
                  f"p50={qs[0.5] * 1e3:.3f}ms "
                  f"p99={qs[0.99] * 1e3:.3f}ms "
                  f"p999={qs[0.999] * 1e3:.3f}ms", file=sys.stderr)
    return 0
