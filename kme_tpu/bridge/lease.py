"""Leader-epoch lease: the fencing token behind exactly-once output.

The reference left Kafka's exactly-once path commented out
(KProcessor.java:29) and ran at-least-once; we replace the transactional
coordinator with the two cheap primitives a deterministic engine needs:

- a monotonically increasing **epoch** handed to each serve incarnation
  (this module: a JSON lease file next to the checkpoints), and
- broker-side **fencing + idempotent produce** keyed on the
  ``(epoch, out_seq)`` stamp each leader puts on its MatchOut records
  (bridge/broker.py).

The lease file is NOT a distributed lock — single-host supervision
(bridge/supervise.py) means at most one writer mutates it at a time.
Races between a dying leader and a promoting standby are resolved where
it matters, at the broker: the larger epoch fences the smaller one, so
even a stale incarnation that still holds an old epoch can never make a
write visible (its produce raises BrokerFenced). ``steal`` exists for
the ``lease.steal`` fault point: it simulates exactly that split-brain
by advancing the epoch out from under the running leader.
"""

from __future__ import annotations

import json
import os
import time

LEASE_FILE = "lease.json"


def _path(ckpt_dir: str) -> str:
    return os.path.join(ckpt_dir, LEASE_FILE)


def read(ckpt_dir: str) -> dict:
    """The raw lease record; {} when absent or unreadable (a torn lease
    write loses at most the latest grant — the next acquire re-reads
    epoch 0 and the broker's recovered fence still rejects true
    staleness, so corruption degrades to a slower restart, not a
    duplicate)."""
    try:
        with open(_path(ckpt_dir), encoding="utf-8") as f:
            rec = json.load(f)
        return rec if isinstance(rec, dict) else {}
    except (OSError, ValueError):
        return {}


def current_epoch(ckpt_dir: str) -> int:
    """Highest epoch ever granted from this checkpoint dir (0 = none)."""
    try:
        return int(read(ckpt_dir).get("epoch", 0))
    except (TypeError, ValueError):
        return 0


def _grant(ckpt_dir: str, role: str, events=None) -> int:
    os.makedirs(ckpt_dir, exist_ok=True)
    epoch = current_epoch(ckpt_dir) + 1
    tmp = _path(ckpt_dir) + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump({"epoch": epoch, "pid": os.getpid(),
                   "time": time.time(), "role": role}, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, _path(ckpt_dir))
    if events is not None:
        # flight recorder: every epoch transition is a fencing event —
        # the caller's EventLog, so the timeline attributes the grant
        # to the process that took it. No pid in the payload (the
        # lease file keeps it): event bytes stay replay-deterministic,
        # which the sim's timeline-digest verdict depends on
        try:
            events.emit("lease.steal" if role == "stolen"
                        else "lease.grant",
                        severity="warn" if role == "stolen" else "info",
                        epoch=epoch, role=role)
        except Exception:
            pass
    return epoch


def acquire(ckpt_dir: str, events=None) -> int:
    """Grant the next leader epoch to the calling process."""
    return _grant(ckpt_dir, "leader", events=events)


def steal(ckpt_dir: str, events=None) -> int:
    """Advance the epoch WITHOUT the current leader's cooperation (the
    ``lease.steal`` split-brain drill — and the reshard coordinator's
    per-group fence)."""
    return _grant(ckpt_dir, "stolen", events=events)
