"""kme-chaos: deterministic fault-injection runs with byte-exact verify.

The recovery stack (kme-supervise -> checkpoint/resume -> at-least-once
replay) is only trustworthy if something attacks it on purpose. This
harness is that something: it runs a seeded workload through a
supervised kme-serve while a KME_FAULTS schedule (kme_tpu/faults.py)
injects broker I/O errors, partial TCP frames, torn and bit-flipped
snapshots, torn journal tails, SIGKILLs at exact input offsets and
stuck serve loops — then requires the COMPLETED MatchOut stream to be
byte-exact against an in-process oracle replay of the same input,
modulo the at-least-once duplication the recovery contract explicitly
permits (crash -> resume from snapshot -> replay of the input tail).

Everything is deterministic from --seed: the workload
(kme_tpu.workload.harness_stream) and every fault rule's RNG derive
from it, so a failing run reproduces from its report's spec string.

The run:

1. compute the oracle's expected per-message output groups in-process;
2. start `kme-supervise -- kme-serve ...` with KME_FAULTS +
   KME_FAULTS_STATE in its environment (the state dir makes n-limited
   rules fire once across ALL child incarnations);
3. produce the input over the TCP broker protocol, idempotently:
   transport faults reconnect + resync from end_offset(MatchIn), and
   wire-level rej_overload (the bounded-ingress shed) backs off and
   retries — input content is never duplicated or dropped;
4. wait for the supervisor to exit (the child exits cleanly once the
   input is drained and --idle-exit lapses);
5. read the durable MatchOut topic log post-mortem and verify it is a
   prefix+replay composition of the oracle groups (verify_stream);
6. emit a JSON report: verification result, restarts, replayed
   messages, per-fault fire counts, measured recovery times.

Exit 0 iff the stream verifies, the supervisor exited cleanly and at
least --min-restarts automatic restarts happened (a chaos run where
nothing died proves nothing).

--scenario failover drills the exactly-once failover stack instead:
the leader runs with a hot standby (kme-supervise --standby), one
seeded SIGKILL lands mid-stream, and the run only passes if the
supervisor promoted the replica within --max-failover seconds, the
promoted epoch is visible in the log's produce stamps, a stale-epoch
produce is fenced post-mortem, broker-side dedup suppressed the
promoted leader's replayed overlap (dup_suppressed_total > 0), and the
deduped MatchOut stream is BYTE-EXACT against the flat oracle stream —
zero visible duplicates (verify_failover), a strictly stronger contract
than verify_stream's at-least-once composition.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import threading
import time
from typing import List, Optional, Tuple

from kme_tpu.bridge.service import TOPIC_IN, TOPIC_OUT


def default_schedule(seed: int, events: int, journal: bool) -> str:
    """A schedule touching every layer: transport, snapshot integrity,
    journal tail, process death and a hung loop. Offsets scale with the
    workload so the kill lands mid-stream and the stall near the end."""
    kill_at = max(1, events // 2)
    stuck_at = max(2, (events * 3) // 4)
    clauses = [f"seed={seed}",
               "broker.fetch:n=2",          # service poll errors (retried)
               "broker.produce:n=1:after=20",   # producer-side I/O error
               "tcp.partial:n=1:after=10",  # poisoned client stream
               "ckpt.torn:n=1:after=1",     # 2nd snapshot truncated
               "ckpt.bitflip:n=1:after=2",  # 3rd snapshot corrupted
               f"serve.kill:at={kill_at}",  # SIGKILL mid-stream
               f"serve.stuck:at={stuck_at}"]  # hung step() near the end
    if journal:
        clauses.append("journal.torn:n=1:after=5")  # crash mid-append
    return ";".join(clauses)


def failover_schedule(seed: int, events: int) -> str:
    """The failover scenario's schedule: ONE clean SIGKILL mid-stream.
    The point under test is the promotion machinery (standby adoption,
    epoch fencing, idempotent-produce dedup of the replayed overlap),
    so no other fault muddies the failure fingerprint or the timing."""
    return f"seed={seed};serve.kill:at={max(1, events // 2)}"


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def expected_groups(lines: List[str], slots: int,
                    max_fills: int) -> List[List[str]]:
    """The oracle's per-input-message MatchOut line groups — the ground
    truth the durable stream must compose from."""
    from kme_tpu.oracle import OracleEngine
    from kme_tpu.wire import parse_order

    eng = OracleEngine("fixed", book_slots=slots, max_fills=max_fills)
    return [[rec.wire() for rec in eng.process(parse_order(ln))]
            for ln in lines]


def verify_stream(got: List[str], per_msg: List[List[str]]
                  ) -> Tuple[bool, dict]:
    """Check `got` (the durable MatchOut lines) against the oracle
    groups under the at-least-once contract: the stream must be a
    concatenation of segments, each a run of consecutive whole groups,
    where a segment may end mid-group (crash between produces) and the
    next segment restarts at an EARLIER group (replay from a snapshot).
    Every group must eventually complete in order. Returns
    (ok, {messages, replays, replayed_messages, got_lines,
    expected_lines, error})."""
    i = j = 0               # i: cursor in got, j: next group to complete
    replays = replayed = 0
    detail: dict = {"got_lines": len(got),
                    "expected_lines": sum(len(g) for g in per_msg),
                    "messages": len(per_msg)}
    while i < len(got) or j < len(per_msg):
        exp = per_msg[j] if j < len(per_msg) else None
        if exp is not None and got[i:i + len(exp)] == exp \
                and i + len(exp) <= len(got):
            i += len(exp)
            j += 1
            continue
        # mismatch, short tail, or all groups done with got remaining:
        # this must be a crash point. Consume any partial prefix of the
        # current group (the child died between produces of one batch)…
        p = 0
        if exp is not None:
            while (p < len(exp) and i + p < len(got)
                   and got[i + p] == exp[p]):
                p += 1
        i += p
        if i >= len(got):
            if j < len(per_msg):
                detail["error"] = (f"stream ends early: group {j} of "
                                   f"{len(per_msg)} incomplete")
                return False, detail
            break
        # …then the next durable line must start a REPLAY: a run that
        # begins at some group S <= j (the snapshot the child resumed
        # from). Prefer the largest S (minimal replay).
        found = None
        for S in range(j, -1, -1):
            e2 = per_msg[S] if S < len(per_msg) else None
            if e2 and got[i:i + len(e2)] == e2:
                found = S
                break
        if found is None or (found == j and p == 0):
            detail["error"] = (f"byte divergence at line {i} "
                               f"(group {j}): {got[i][:100]!r}")
            return False, detail
        replays += 1
        replayed += sum(1 for g in per_msg[found:j] if g) + (1 if p else 0)
        j = found
    if j < len(per_msg):
        detail["error"] = (f"only {j} of {len(per_msg)} groups "
                           f"completed")
        return False, detail
    detail["replays"] = replays
    detail["replayed_messages"] = replayed
    return True, detail


class _Producer(threading.Thread):
    """Idempotent MatchIn feeder: re-syncs from end_offset after any
    transport fault (so injected tcp.partial / disconnects / broker
    errors never duplicate or drop input) and treats rej_overload as
    backpressure (sleep + retry the SAME record)."""

    def __init__(self, host: str, port: int, lines: List[str],
                 topic: str = TOPIC_IN, topics=None) -> None:
        super().__init__(daemon=True)
        self.host, self.port, self.lines = host, port, lines
        self.topic = topic
        self.topics = topics      # provision set (None = classic pair)
        self.sent = 0
        self.overload_retries = 0
        self.reconnects = 0
        self.stop = threading.Event()

    def run(self) -> None:
        from kme_tpu.bridge.broker import BrokerError, BrokerOverload
        from kme_tpu.bridge.provision import provision
        from kme_tpu.bridge.tcp import TcpBroker

        client = None
        while self.sent < len(self.lines) and not self.stop.is_set():
            try:
                if client is None:
                    client = TcpBroker(self.host, self.port, timeout=10.0)
                    provision(client, topics=self.topics)   # idempotent
                    self.sent = client.end_offset(self.topic)
                client.produce(self.topic, None, self.lines[self.sent])
                self.sent += 1
            except BrokerOverload:
                self.overload_retries += 1
                time.sleep(0.05)
            except (BrokerError, OSError):
                # transport fault or the child is restarting: reconnect
                # and resync the resume point from the durable log
                if client is not None:
                    try:
                        client.close()
                    except OSError:
                        pass
                client = None
                self.reconnects += 1
                time.sleep(0.2)
        if client is not None:
            try:
                client.close()
            except OSError:
                pass


def read_matchout_records(log_dir: str, topic: str = TOPIC_OUT) -> list:
    """Post-mortem read of a durable topic log (the broker persists
    topics as JSONL under the checkpoint dir) as Records — produce
    stamps included."""
    from kme_tpu.bridge.broker import BrokerError, InProcessBroker

    broker = InProcessBroker(persist_dir=log_dir)
    out: list = []
    try:
        while True:
            recs = broker.fetch(topic, len(out), 4096, timeout=0.0)
            if not recs:
                return out
            out.extend(recs)
    except BrokerError:
        return out          # topic never created (nothing got through)
    finally:
        if hasattr(broker, "close"):
            broker.close()


def read_matchout(log_dir: str) -> List[str]:
    return [f"{r.key} {r.value}" for r in read_matchout_records(log_dir)]


def verify_failover(recs: list, per_msg: List[List[str]],
                    max_epoch_floor: int = 2) -> Tuple[bool, dict]:
    """The exactly-once failover contract over the durable MatchOut
    records: after consumer-side dedup (bridge/consume.DedupRing) the
    visible stream must be BYTE-EXACT equal to the flat oracle stream —
    zero duplicates, zero gaps, zero reordering — and the log must show
    at least two leader epochs (the promotion really happened). The
    broker already suppresses replayed stamps at produce time, so the
    raw log itself should carry no duplicate stamps either; any the
    ring finds are counted and failed on."""
    from kme_tpu.bridge.consume import DedupRing

    ring = DedupRing()
    visible = [f"{r.key} {r.value}" for r in recs
               if not ring.is_dup(r.epoch, r.out_seq)]
    flat = [ln for g in per_msg for ln in g]
    epochs = sorted({r.epoch for r in recs if r.epoch is not None})
    detail = {"got_lines": len(visible),
              "expected_lines": len(flat),
              "messages": len(per_msg),
              "duplicates_in_log": ring.suppressed,
              "unstamped_records": sum(1 for r in recs
                                       if r.epoch is None),
              "epochs": epochs}
    ok = True
    if ring.suppressed:
        detail["error"] = (f"{ring.suppressed} duplicate produce "
                           f"stamp(s) reached the durable log")
        ok = False
    elif visible != flat:
        n = min(len(visible), len(flat))
        div = next((k for k in range(n) if visible[k] != flat[k]), n)
        detail["error"] = (f"deduped stream diverges from the oracle "
                           f"at line {div} (got {len(visible)} lines, "
                           f"want {len(flat)})")
        ok = False
    elif not epochs or epochs[-1] < max_epoch_floor:
        detail["error"] = (f"no promoted epoch in the log (epochs "
                           f"{epochs}); failover never happened")
        ok = False
    return ok, detail


def _check_failover(ckpt_dir: str, log_dir: str, recoveries: list,
                    max_failover: float, failures: List[str]) -> dict:
    """Failover-scenario assertions beyond stream byte-exactness:
    bounded promotion, broker-side dedup actually observed, and a
    stale-epoch produce fenced post-mortem. Appends human-readable
    reasons to `failures`; returns the report sub-dict."""
    out: dict = {}
    promoted = [r for r in recoveries if r.get("promoted")]
    fo = [r["failover_seconds"] for r in promoted
          if r.get("failover_seconds") is not None]
    out["promotions"] = len(promoted)
    out["failover_seconds"] = fo
    if not promoted:
        failures.append("no hot-standby promotion recorded by the "
                        "supervisor")
    elif fo and max(fo) > max_failover:
        failures.append(f"failover took {max(fo):.2f}s "
                        f"(bound {max_failover}s)")

    # the promoted leader's final heartbeat carries the broker-side
    # exactly-once counters: the replayed overlap MUST have been
    # suppressed by the idempotent-produce watermark, otherwise the
    # byte-exact stream above proved nothing about dedup
    dup = fenced = None
    try:
        with open(os.path.join(ckpt_dir, "serve.health")) as f:
            gauges = json.load(f).get("metrics", {}).get("gauges", {})
        dup = gauges.get("dup_suppressed_total")
        fenced = gauges.get("fenced_produces_total")
        out["leader_epoch"] = gauges.get("leader_epoch")
    except (OSError, ValueError):
        pass
    out["dup_suppressed_total"] = dup
    out["fenced_produces_total"] = fenced
    if not dup:
        failures.append("dup_suppressed_total == 0: the promoted "
                        "leader's replayed overlap never exercised "
                        "broker-side dedup")

    # stale-epoch probe: reload the durable logs the way a recovered
    # broker would and produce with epoch 1 — the fence recovered from
    # the log's stamps must reject it BEFORE anything is appended
    from kme_tpu.bridge.broker import BrokerFenced, InProcessBroker

    probe = InProcessBroker(persist_dir=log_dir)
    try:
        try:
            probe.produce(TOPIC_OUT, "OUT", "stale-epoch-probe",
                          epoch=1, out_seq=10 ** 9)
            out["stale_epoch_fenced"] = False
            failures.append("a stale-epoch (zombie leader) produce was "
                            "NOT fenced post-mortem")
        except BrokerFenced:
            out["stale_epoch_fenced"] = True
    finally:
        if hasattr(probe, "close"):
            probe.close()
    return out


def _timeline_section(run_dir: str, tail: int = 12) -> dict:
    """Merge the run's control-plane event logs into the report: the
    causally ordered timeline of what the cluster DECIDED (spawns,
    crash fingerprints, promotions, lease grants, overload
    transitions) during the drill. Also writes the merged
    ``events.jsonl`` artifact next to the per-process logs so
    ``kme-events <run_dir>`` and CI artifact uploads find one file."""
    from kme_tpu.telemetry import events as cpevents

    try:
        timeline = cpevents.merge_logs([run_dir])
    except OSError:
        return {"count": 0, "digest": None, "tail": []}
    merged_path = os.path.join(run_dir, "events.jsonl")
    try:
        cpevents.write_merged(timeline, merged_path)
    except OSError:
        merged_path = None
    return {"count": len(timeline),
            "digest": cpevents.timeline_digest(timeline),
            "merged_path": merged_path,
            "tail": [cpevents.format_event(ev)
                     for ev in timeline[-tail:]]}


def _busy_rate(samples: List[Tuple[float, int]],
               t_lo: float, t_hi: float) -> Optional[float]:
    """Offset-advance rate (msgs/s) of a heartbeat sample series inside
    [t_lo, t_hi], restricted to the series' BUSY interval (before the
    offset reached its final value — a group that already drained its
    substream cannot be slowed down by anything). None = the window
    holds no measurable busy samples."""
    if len(samples) < 2:
        return None
    final = samples[-1][1]
    busy_end = next((t for t, off in samples if off >= final),
                    samples[-1][0])
    lo, hi = max(t_lo, samples[0][0]), min(t_hi, busy_end)
    win = [(t, off) for t, off in samples if lo <= t <= hi]
    if len(win) < 2 or win[-1][0] <= win[0][0]:
        return None
    return (win[-1][1] - win[0][1]) / (win[-1][0] - win[0][0])


def run_shard_failover(args, run_dir: str, report_path: str) -> int:
    """--scenario shard-failover: the multi-leader drill (ISSUE 9). N
    shard groups (bridge/front.py split, per-group namespaced topics,
    per-group supervisors) serve concurrently; the busiest group's
    leader runs with a hot standby and eats ONE seeded SIGKILL
    mid-substream. Passes iff:

    - the victim's standby promoted within --max-failover seconds;
    - every SURVIVING group kept serving: zero restarts, clean exit,
      and its busy-window throughput during the victim's outage dipped
      < 10% vs its own full-run rate (measured from 10 Hz heartbeat
      offset samples; a survivor that had already drained is exempt —
      nothing was left to slow down);
    - the merged MatchOut (all groups' durable MatchOut.gK + Xfer.gK
      logs, consumer-deduped, re-zipped on the shared out_seq cursor)
      is BYTE-EXACT vs the partitioned single-leader oracle
      (front.verify_groups — the COMPAT.md convention);
    - ZERO duplicate (epoch, out_seq) stamps in ANY durable log: the
      victim's replayed overlap (MatchOut and regenerated transfer
      legs alike) must have been suppressed by the idempotent-produce
      watermark, never appended twice;
    - a stale-epoch produce against the victim's MatchOut is fenced
      post-mortem (no zombie leader can dirty the healed log).
    """
    from kme_tpu.bridge import front
    from kme_tpu.bridge.broker import BrokerFenced, InProcessBroker
    from kme_tpu.bridge.consume import DedupRing
    from kme_tpu.bridge.provision import group_topics
    from kme_tpu.wire import dumps_order
    from kme_tpu.workload import cross_account_stream

    groups = args.groups
    # every group must carry real flow for the drill to mean anything:
    # with few symbols the zipf head lands in one group and the others
    # drain before the kill, leaving the dip check nothing to measure —
    # a wide symbol universe balances the rendezvous placement
    symbols = max(args.symbols, 64 * groups)
    accounts = max(args.accounts, 8 * groups)
    msgs = cross_account_stream(args.events, symbols, accounts, groups,
                                seed=args.seed,
                                cross_frac=args.cross_frac)
    lines = [dumps_order(m) for m in msgs]
    per_group, router = front.split_lines(lines, groups,
                                          prefund=args.prefund)
    # durable copy of the front's input stream: kme-trace --cluster
    # stitches this run dir post-mortem (dtrace.stitch_state_root
    # re-runs the deterministic split over front.in to rebuild the
    # global-offset -> (group, local index) map)
    with open(os.path.join(run_dir, "front.in"), "w") as f:
        f.write("\n".join(lines) + "\n")
    sizes = [len(s) for s in per_group]
    if min(sizes) == 0:
        print(f"kme-chaos: substream sizes {sizes} — empty group; "
              f"raise --symbols", file=sys.stderr)
        return 2
    victim = max(range(groups), key=lambda k: sizes[k])
    # land the kill while EVERY group is still mid-substream (the
    # groups drain concurrently at similar rates, so half the smallest
    # substream is mid-flight for all of them) — otherwise the
    # survivors are already idle and the dip check has nothing to
    # measure
    kill_at = max(1, min(sizes) // 2)
    schedule = f"seed={args.seed};serve.kill:at={kill_at}"
    print(f"kme-chaos: scenario=shard-failover seed={args.seed} "
          f"groups={groups} substreams={sizes} victim=g{victim} "
          f"kill_at={kill_at}\nkme-chaos: run dir {run_dir}",
          file=sys.stderr)

    sups, producers, gdirs = [], [], []
    t0 = time.time()
    for k in range(groups):
        gdir = os.path.join(run_dir, f"group{k}")
        ckpt = os.path.join(gdir, "state")
        os.makedirs(ckpt, exist_ok=True)
        gdirs.append(gdir)
        port = _free_port()
        serve_args = ["--engine", args.engine, "--compat", "fixed",
                      "--batch", str(args.batch),
                      "--slots", str(args.slots),
                      "--max-fills", str(args.max_fills),
                      "--checkpoint-every", str(args.checkpoint_every),
                      "--checkpoint-keep", str(args.checkpoint_keep),
                      "--group", f"{k}/{groups}",
                      "--listen", f"127.0.0.1:{port}",
                      "--idle-exit", str(args.idle_exit),
                      "--health-every", "0.1",
                      # per-group latency journal + span tracing: the
                      # post-mortem stitches every admitted order into
                      # a cluster waterfall (journal resume=True, so a
                      # restarted leader appends after the kill)
                      "--journal-out",
                      os.path.join(ckpt, "journal.bin"),
                      "--trace-spans"]
        if args.engine == "seq":
            # pipelined submit/collect arms the async dispatch + H2D
            # double-buffer path (r14) inside each group's leader, so
            # the failover drill exercises promotion/replay against
            # in-flight device work rather than the serial loop
            serve_args += ["--pipeline", "1"]
        sup_cmd = [sys.executable, "-m", "kme_tpu.cli", "supervise",
                   "--checkpoint-dir", ckpt,
                   "--stale-after", str(args.stale_after),
                   "--stall-after", str(args.stall_after),
                   "--max-restarts", str(args.max_restarts),
                   "--grace", str(args.grace),
                   "--backoff-base", "0.05", "--backoff-cap", "0.5"]
        if k == victim:
            sup_cmd += ["--standby", "--poll", "0.1"]
        sup_cmd += ["--"] + serve_args
        env = dict(os.environ)
        env.pop("KME_FAULTS", None)       # survivors run fault-free
        env.pop("KME_FAULTS_STATE", None)
        if k == victim:
            env["KME_FAULTS"] = schedule
            env["KME_FAULTS_STATE"] = os.path.join(gdir, "fault-state")
        env.setdefault("JAX_PLATFORMS", "cpu")
        sups.append(subprocess.Popen(sup_cmd, env=env))
        prod = _Producer("127.0.0.1", port, per_group[k],
                         topic=group_topics(k)[0],
                         topics=group_topics(k))
        prod.start()
        producers.append(prod)

    # 10 Hz heartbeat sampling: (wall time, input offset) per group —
    # the survivors' liveness evidence during the victim's outage
    samples: dict = {k: [] for k in range(groups)}
    stop = threading.Event()

    def monitor() -> None:
        while not stop.wait(0.1):
            for k in range(groups):
                try:
                    with open(os.path.join(gdirs[k], "state",
                                           "serve.health")) as f:
                        hb = json.load(f)
                    samples[k].append((time.time(),
                                       int(hb.get("offset", 0))))
                except (OSError, ValueError, TypeError):
                    pass

    mon = threading.Thread(target=monitor, daemon=True)
    mon.start()

    rcs: List[Optional[int]] = [None] * groups
    deadline = t0 + args.timeout
    while time.time() < deadline:
        rcs = [s.poll() for s in sups]
        if all(rc is not None for rc in rcs):
            break
        time.sleep(0.25)
    for s in sups:
        if s.poll() is None:
            print("kme-chaos: TIMEOUT; killing a supervisor",
                  file=sys.stderr)
            s.kill()
            s.wait()
    rcs = [s.returncode for s in sups]
    stop.set()
    mon.join(timeout=2.0)
    for prod in producers:
        prod.stop.set()
        prod.join(timeout=10.0)
    elapsed = time.time() - t0

    failures: List[str] = []
    for k in range(groups):
        if rcs[k] != 0:
            failures.append(f"group {k} supervisor exited rc={rcs[k]}")
        if producers[k].sent < sizes[k]:
            failures.append(f"group {k} producer delivered "
                            f"{producers[k].sent} of {sizes[k]}")

    # victim: promotion happened, and within the bound
    sup_states = []
    for k in range(groups):
        st = {}
        try:
            with open(os.path.join(gdirs[k], "state",
                                   "supervisor.json")) as f:
                st = json.load(f)
        except (OSError, ValueError):
            pass
        sup_states.append(st)
    promoted = [r for r in sup_states[victim].get("recoveries", [])
                if r.get("promoted")]
    fo = [r["failover_seconds"] for r in promoted
          if r.get("failover_seconds") is not None]
    if not promoted:
        failures.append("victim group never promoted its standby")
    elif fo and max(fo) > args.max_failover:
        failures.append(f"failover took {max(fo):.2f}s "
                        f"(bound {args.max_failover}s)")

    # survivors: no restarts, and the throughput dip during the
    # victim's outage window stays under 10%
    outage = None
    if promoted and promoted[0].get("detected_at") is not None:
        det = float(promoted[0]["detected_at"])
        outage = (det, det + float(promoted[0].get("recovered_in", 0)))
    dips: dict = {}
    for k in range(groups):
        if k == victim:
            continue
        restarts = int(sup_states[k].get("restarts_total", 0))
        if restarts:
            failures.append(f"surviving group {k} restarted "
                            f"{restarts}x during the drill")
        full = _busy_rate(samples[k], 0.0, float("inf"))
        win = (_busy_rate(samples[k], *outage)
               if outage is not None else None)
        if full and win is not None:
            dip = max(0.0, 1.0 - win / full)
            dips[f"g{k}"] = round(dip, 4)
            if dip >= 0.10:
                failures.append(f"surviving group {k} throughput "
                                f"dipped {dip:.0%} during failover "
                                f"(bound 10%)")
        else:
            # drained before the outage (or the window was too short
            # to hold two 10 Hz samples): nothing left to slow down
            dips[f"g{k}"] = None

    # durable logs: dedup per topic (ZERO duplicate stamps anywhere),
    # then re-zip each group's MatchOut + Xfer on the shared out_seq
    # cursor and verify the merged stream against the oracle
    dup_stamps: dict = {}
    actual: List[List[str]] = []
    for k in range(groups):
        log_dir = os.path.join(gdirs[k], "state", "broker-log")
        merged = []
        for topic in (group_topics(k)[1], group_topics(k)[2]):
            recs = read_matchout_records(log_dir, topic=topic)
            ring = DedupRing()
            keep = [r for r in recs if not ring.is_dup(r.epoch,
                                                       r.out_seq)]
            dup_stamps[topic] = ring.suppressed
            if ring.suppressed:
                failures.append(f"{ring.suppressed} duplicate "
                                f"(epoch,out_seq) stamp(s) in the "
                                f"durable {topic} log")
            merged.extend(keep)
        merged.sort(key=lambda r: (r.out_seq
                                   if r.out_seq is not None else -1))
        actual.append([f"{r.key} {r.value}" for r in merged])
    verify = front.verify_groups(lines, actual, compat="fixed",
                                 book_slots=args.slots,
                                 max_fills=args.max_fills,
                                 prefund=args.prefund)
    if not verify["ok"]:
        failures.append(f"merged stream diverged from the single-"
                        f"leader oracle: {verify['mismatches'][:1]}")

    # trace integrity post-mortem: the per-group span journals must
    # stitch into exactly one complete waterfall per admitted order.
    # The victim's replayed overlap dedups away by the durable
    # (group, local_off, kind) key — first occurrence wins, mirroring
    # the broker's (epoch, out_seq) dedup — and the standby promotion
    # shows as a span GAP inside one waterfall, never a forked second
    # trace for the same order.
    from kme_tpu.telemetry import dtrace
    from kme_tpu.telemetry.journal import read_events
    trace_post: dict = {}
    try:
        tdoc = dtrace.stitch_state_root(run_dir,
                                        prefund=args.prefund)
        frac = (tdoc["stitched"] / tdoc["admitted"]
                if tdoc["admitted"] else 0.0)
        offs = [o["off"] for o in tdoc["orders"]]
        forked = len(offs) - len(set(offs))
        # raw replay overlap in the victim's journal (pre-dedup):
        # span records the restarted leader re-journaled for offsets
        # the dead leader had already covered
        replay_dups = 0
        jp = dtrace._find_journal(gdirs[victim])
        if jp is not None:
            seen = set()
            for ev in read_events(jp):
                if ev.get("e") == "span":
                    key = (ev.get("off"), ev.get("kind"))
                    if key in seen:
                        replay_dups += 1
                    else:
                        seen.add(key)
        trace_post = {"admitted": tdoc["admitted"],
                      "stitched": tdoc["stitched"],
                      "stitched_frac": round(frac, 5),
                      "forked_waterfalls": forked,
                      "victim_replayed_spans_deduped": replay_dups}
        if tdoc["admitted"] == 0:
            failures.append("tracing: stitched trace admitted zero "
                            "orders")
        elif frac < 0.999:
            failures.append(f"tracing: only {frac:.2%} of admitted "
                            f"orders stitched into complete cluster "
                            f"waterfalls (bound 99.9%)")
        if forked:
            failures.append(f"tracing: {forked} order(s) forked a "
                            f"second waterfall across the failover")
    except (OSError, ValueError) as e:
        trace_post = {"error": str(e)}
        failures.append(f"tracing: post-mortem stitch failed: {e}")

    # zombie fence: a stale-epoch produce against the victim's healed
    # MatchOut log must be rejected before anything is appended
    probe = InProcessBroker(persist_dir=os.path.join(
        gdirs[victim], "state", "broker-log"))
    stale_fenced = False
    try:
        try:
            probe.produce(group_topics(victim)[1], "OUT",
                          "stale-epoch-probe", epoch=1, out_seq=10 ** 9)
            failures.append("a stale-epoch produce against the "
                            "victim's MatchOut was NOT fenced")
        except BrokerFenced:
            stale_fenced = True
    finally:
        if hasattr(probe, "close"):
            probe.close()

    report = {
        "ok": not failures,
        "failures": failures,
        "scenario": "shard-failover",
        "seed": args.seed,
        "events": len(msgs),
        "groups": groups,
        "victim": victim,
        "substreams": sizes,
        "schedule": schedule,
        "elapsed_seconds": round(elapsed, 3),
        "promotions": len(promoted),
        "failover_seconds": fo,
        "survivor_dips": dips,
        "outage_window_s": (round(outage[1] - outage[0], 3)
                            if outage else None),
        "duplicate_stamps": dup_stamps,
        "cross_shard_transfers":
            router.counters["cross_shard_transfers_total"],
        "trace": trace_post,
        "stale_epoch_fenced": stale_fenced,
        "verify": dict(verify,
                       mismatches=verify.get("mismatches", [])[:3]),
        "supervisors": sup_states,
        "timeline": _timeline_section(run_dir),
        "run_dir": run_dir,
    }
    with open(report_path, "w") as f:
        json.dump(report, f, indent=1)
    status = "OK" if report["ok"] else "FAILED"
    print(f"kme-chaos: {status} — shard-failover groups={groups} "
          f"victim=g{victim} promotions={len(promoted)} "
          f"failover_seconds={fo} dips={dips} "
          f"dup_stamps={sum(dup_stamps.values())} "
          f"waterfalls={trace_post.get('stitched')}/"
          f"{trace_post.get('admitted')} "
          f"stale_epoch_fenced={stale_fenced} parity="
          f"{'byte-exact' if verify['ok'] else 'DIVERGED'} "
          f"elapsed={elapsed:.1f}s", file=sys.stderr)
    for fail in failures:
        print(f"kme-chaos: FAIL: {fail}", file=sys.stderr)
    print(f"kme-chaos: report written to {report_path}",
          file=sys.stderr)
    return 0 if report["ok"] else 1


def run_feed_failover(args, run_dir: str, report_path: str) -> int:
    """--scenario feed-failover: the market-data read path under the
    write path's failover (ISSUE 13). A supervised kme-serve runs with
    a hot standby and eats ONE seeded SIGKILL mid-stream while a real
    kme-feed fan-out tier (FeedServer over a TcpBroker, reconnect
    armed) serves LIVE subscribers — one wildcard auditor plus filtered
    single/multi-symbol subs. Passes iff:

    - the standby promoted (and within --max-failover seconds);
    - the feed tier actually rode through the outage: at least one
      broker reconnect fired, and the feed consumed the full durable
      MatchOut log;
    - every subscriber's reconstructed book is BYTE-EXACT
      (canonical_books) against an in-process oracle replay of the
      input, restricted to its subscription — the deriver on the
      promoted leader's replayed tail regenerated the exact frames the
      dead one would have sent;
    - ZERO missing and ZERO duplicate per-symbol delta seqs on every
      subscriber (BookBuilder gap/dup accounting), across the kill,
      the reconnect and any conflation/resync cycles.
    """
    from kme_tpu.bridge.tcp import TcpBroker
    from kme_tpu.feed.client import FeedClient
    from kme_tpu.feed.derive import books_from_oracle, canonical_books
    from kme_tpu.feed.server import FeedServer
    from kme_tpu.oracle import OracleEngine
    from kme_tpu.telemetry import Registry
    from kme_tpu.wire import dumps_order, parse_order
    from kme_tpu.workload import harness_stream

    ckpt_dir = os.path.join(run_dir, "state")
    state_dir = os.path.join(run_dir, "fault-state")
    os.makedirs(ckpt_dir, exist_ok=True)
    schedule = args.schedule or failover_schedule(args.seed, args.events)
    print(f"kme-chaos: scenario=feed-failover seed={args.seed} "
          f"events={args.events}\nkme-chaos: schedule {schedule}\n"
          f"kme-chaos: run dir {run_dir}", file=sys.stderr)

    # ground truth: oracle replay of the input under the same envelope
    # the serve runs with; the final resting-order store is what every
    # subscriber book must reduce to
    msgs = harness_stream(args.events, seed=args.seed,
                          num_accounts=args.accounts,
                          num_symbols=max(args.symbols, 6),
                          payout_opcode_bug=False, validate=True)
    lines = [dumps_order(m) for m in msgs]
    eng = OracleEngine("fixed", book_slots=args.slots,
                       max_fills=args.max_fills)
    for ln in lines:
        eng.process(parse_order(ln))
    oracle_levels = books_from_oracle(eng)
    book_sids = sorted({sid for sid, _ in oracle_levels}) or [1]

    # the supervised write path, hot standby armed, one seeded SIGKILL
    port = _free_port()
    serve_args = ["--engine", args.engine, "--compat", "fixed",
                  "--batch", str(args.batch),
                  "--slots", str(args.slots),
                  "--max-fills", str(args.max_fills),
                  "--checkpoint-every", str(args.checkpoint_every),
                  "--checkpoint-keep", str(args.checkpoint_keep),
                  "--listen", f"127.0.0.1:{port}",
                  "--idle-exit", str(args.idle_exit),
                  "--health-every", "0.2"]
    sup_cmd = [sys.executable, "-m", "kme_tpu.cli", "supervise",
               "--checkpoint-dir", ckpt_dir,
               "--stale-after", str(args.stale_after),
               "--stall-after", str(args.stall_after),
               "--max-restarts", str(args.max_restarts),
               "--grace", str(args.grace),
               "--backoff-base", "0.05", "--backoff-cap", "0.5",
               "--standby", "--poll", "0.1", "--"] + serve_args
    env = dict(os.environ)
    env["KME_FAULTS"] = schedule
    env["KME_FAULTS_STATE"] = state_dir
    env.setdefault("JAX_PLATFORMS", "cpu")
    t0 = time.time()
    sup = subprocess.Popen(sup_cmd, env=env)

    # the feed tier: reconnect armed (and counted — the drill requires
    # the outage to have actually hit the read path)
    reconnects = [0]

    def _factory():
        reconnects[0] += 1
        return TcpBroker("127.0.0.1", port, timeout=5.0)

    # the supervised serve is still booting: retry the initial connect
    boot_deadline = time.time() + 30.0
    while True:
        try:
            broker0 = TcpBroker("127.0.0.1", port, timeout=5.0)
            break
        except OSError:
            if time.time() > boot_deadline:
                raise
            time.sleep(0.2)
    registry = Registry()
    feed = FeedServer(broker0, port=0, topic=TOPIC_OUT,
                      depth_every=64, registry=registry,
                      reconnect=_factory)
    stop_ev = threading.Event()
    feed_thread = threading.Thread(target=feed.serve_forever,
                                   args=(stop_ev,), daemon=True)
    feed_thread.start()

    # live subscribers, connected BEFORE the stream flows: a wildcard
    # auditor, a single-symbol sub and a two-symbol sub
    fh, fp = feed.address
    sub_plans = [None, {book_sids[0]},
                 set(book_sids[:2]) if len(book_sids) > 1
                 else {book_sids[0]}]
    clients = [FeedClient(fh, fp, symbols=plan, timeout=1.0)
               for plan in sub_plans]
    done_ev = threading.Event()

    def _drain(c: FeedClient) -> None:
        while not done_ev.is_set():
            got = sum(1 for _ in c.recv_frames())
            if got == 0 and done_ev.is_set():
                return

    client_threads = [threading.Thread(target=_drain, args=(c,),
                                       daemon=True) for c in clients]
    for th in client_threads:
        th.start()

    producer = _Producer("127.0.0.1", port, lines)
    producer.start()

    sup_rc: Optional[int] = None
    deadline = t0 + args.timeout
    while time.time() < deadline:
        sup_rc = sup.poll()
        if sup_rc is not None:
            break
        time.sleep(0.25)
    if sup_rc is None:
        print(f"kme-chaos: TIMEOUT after {args.timeout}s; killing the "
              f"supervisor", file=sys.stderr)
        sup.kill()
        sup.wait()
        sup_rc = sup.returncode
    producer.stop.set()
    producer.join(timeout=10.0)
    elapsed = time.time() - t0

    # the write path is gone; the feed must already hold the whole log
    log_dir = os.path.join(ckpt_dir, "broker-log")
    recs = read_matchout_records(log_dir)
    caught_up = feed.offset >= len(recs)
    lag = registry.latency("feed_lag").quantiles()
    # stop() first: the feed is likely spinning in its reconnect loop
    # now that the write path is gone, and only _stop breaks that
    feed.stop()
    stop_ev.set()
    feed_thread.join(timeout=10.0)
    feed.drain(timeout=10.0)
    stats = feed.stats()
    feed.close()                      # EOF to every subscriber
    done_ev.set()
    for th in client_threads:
        th.join(timeout=10.0)
    for c in clients:
        c.close()

    sup_state = {}
    try:
        with open(os.path.join(ckpt_dir, "supervisor.json")) as f:
            sup_state = json.load(f)
    except (OSError, ValueError):
        pass
    recoveries = sup_state.get("recoveries", [])
    promoted = [r for r in recoveries if r.get("promoted")]
    fo = [r["failover_seconds"] for r in promoted
          if r.get("failover_seconds") is not None]

    failures: List[str] = []
    if sup_rc != 0:
        failures.append(f"supervisor exited rc={sup_rc}")
    if producer.sent < len(lines):
        failures.append(f"producer only delivered {producer.sent} of "
                        f"{len(lines)} records")
    if not promoted:
        failures.append("the standby never promoted")
    elif fo and max(fo) > args.max_failover:
        failures.append(f"failover took {max(fo):.2f}s "
                        f"(bound {args.max_failover}s)")
    if reconnects[0] < 1:
        failures.append("the feed tier never reconnected — the kill "
                        "missed the read path, the drill proves "
                        "nothing")
    if not caught_up:
        failures.append(f"feed consumed {feed.offset} of {len(recs)} "
                        f"durable MatchOut records before the write "
                        f"path exited")
    sub_reports = []
    for ci, c in enumerate(clients):
        bb = c.builder
        want = (oracle_levels if c.symbols is None
                else {k: v for k, v in oracle_levels.items()
                      if k[0] in c.symbols})
        exact = canonical_books(bb.book) == canonical_books(want)
        sub_reports.append({
            "symbols": (sorted(c.symbols)
                        if c.symbols is not None else None),
            "frames": bb.frames, "gaps": len(bb.gaps),
            "dups": bb.dups, "resyncs": bb.resyncs,
            "byte_exact": exact,
        })
        tag = f"subscriber {ci} (symbols={sub_reports[-1]['symbols']})"
        if bb.errors:
            failures.append(f"{tag}: {bb.errors[:2]}")
        if bb.gaps:
            failures.append(f"{tag}: {len(bb.gaps)} missing delta "
                            f"seq range(s), e.g. {bb.gaps[:2]}")
        if bb.dups:
            failures.append(f"{tag}: {bb.dups} duplicate seq(s)")
        if not exact:
            failures.append(f"{tag}: book diverged from the oracle "
                            f"replay post-promotion")

    report = {
        "ok": not failures,
        "failures": failures,
        "scenario": "feed-failover",
        "seed": args.seed,
        "events": args.events,
        "schedule": schedule,
        "elapsed_seconds": round(elapsed, 3),
        "promotions": len(promoted),
        "failover_seconds": fo,
        "feed_reconnects": reconnects[0],
        "feed": stats,
        "feed_lag_p99_ms": round(lag[0.99] * 1e3, 3),
        "subscribers": sub_reports,
        "supervisor": sup_state,
        "fault_fires": _fault_fires(state_dir),
        "timeline": _timeline_section(run_dir),
        "run_dir": run_dir,
    }
    with open(report_path, "w") as f:
        json.dump(report, f, indent=1)
    status = "OK" if report["ok"] else "FAILED"
    print(f"kme-chaos: {status} — feed-failover: promotions="
          f"{len(promoted)} failover_seconds={fo} "
          f"feed_reconnects={reconnects[0]} "
          f"frames={stats['frames']} dup_suppressed="
          f"{stats['dup_suppressed']} books="
          f"{sum(1 for s in sub_reports if s['byte_exact'])}/"
          f"{len(sub_reports)} byte-exact, gaps="
          f"{sum(s['gaps'] for s in sub_reports)}, dups="
          f"{sum(s['dups'] for s in sub_reports)}, "
          f"elapsed={elapsed:.1f}s", file=sys.stderr)
    for fail in failures:
        print(f"kme-chaos: FAIL: {fail}", file=sys.stderr)
    print(f"kme-chaos: report written to {report_path}",
          file=sys.stderr)
    return 0 if report["ok"] else 1


def run_reshard_storm(args, run_dir: str, report_path: str) -> int:
    """--scenario reshard-under-storm: the live N→M topology drill
    (ROADMAP item 2). A funded flash-crowd workload is split across N
    shard groups; at a batch barrier mid-stream the old generation
    drains and the reshard coordinator (bridge/reshard.py) fences the
    old epochs durably, migrates book/position state through the
    checkpoint codec and settles consolidated balances with stamped
    transfer legs — eating one REAL mid-settle SIGKILL and re-running
    to the identical end state — then an M-group new generation resumes
    the suffix over the multi-host front links (front.FrontLinks, real
    TCP, reconnect-with-resume off the out_seq cursor). Passes iff:

    - BYTE PARITY across both generations: each group's deduped durable
      MatchOut + Xfer merge equals the single-leader oracle partitioned
      by the pre/post topologies (front.verify_groups_reshard — the
      resharding-is-pure-topology contract);
    - ZERO duplicate (epoch, out_seq) stamps in ANY durable log of
      either generation, MatchIn included: the crashed coordinator's
      replayed legs and the front's reconnect re-sends must have been
      watermark-suppressed, never appended twice;
    - the settlement survived the crash EXACTLY ONCE: every journaled
      leg appears exactly once in its group's durable MatchIn, the
      re-run visibly suppressed the pre-crash copies, and every new
      group's final pending_reserve checkpoint ledger counts exactly
      coordinator legs + front reserve legs with zero rejects;
    - every old group's log is DURABLY re-fenced (probe_fenced: a
      stale-epoch produce raises BrokerFenced even on a fresh reload);
    - bounded dip: the migration pause (old-generation drain → first
      new-generation progress) stays under --reshard-pause seconds and
      the new generation's final lat_e2e p99 under --reshard-p99-ms
      (the settlement legs are admitted while no leader is up, so that
      histogram deliberately swallows the migration gap).
    """
    import collections
    import signal as _signal

    from kme_tpu import opcodes as op
    from kme_tpu.bridge import front
    from kme_tpu.bridge import reshard as reshard_mod
    from kme_tpu.bridge.broker import BrokerError
    from kme_tpu.bridge.consume import DedupRing
    from kme_tpu.bridge.provision import group_topics, provision
    from kme_tpu.bridge.tcp import TcpBroker
    from kme_tpu.runtime import checkpoint as ck
    from kme_tpu.wire import dumps_order, parse_order
    from kme_tpu.workload import cross_account_stream

    n, m = args.groups, args.groups_to
    engine = args.engine
    if engine != "oracle":
        print(f"kme-chaos: reshard surgery runs on oracle snapshots; "
              f"overriding --engine {engine} -> oracle", file=sys.stderr)
        engine = "oracle"
    # wide universes keep every group busy under BOTH topologies (the
    # shard-failover sizing rule, applied to max(n, m))
    symbols = max(args.symbols, 64 * max(n, m))
    accounts = max(args.accounts, 8 * max(n, m))
    msgs = cross_account_stream(args.events, symbols, accounts, n,
                                seed=args.seed,
                                cross_frac=args.cross_frac)
    lines = [dumps_order(mm) for mm in msgs]
    split_at = len(lines) // 2
    pre_sub, router = front.split_lines(lines[:split_at], n,
                                        prefund=args.prefund)
    reshard_info = router.reshard(m)
    post_sub: List[List[str]] = [[] for _ in range(m)]
    for ln in lines[split_at:]:
        for g, l2 in router.route_line(ln):
            post_sub[g].append(l2)
    sizes_pre = [len(s) for s in pre_sub]
    sizes_post = [len(s) for s in post_sub]
    if min(sizes_pre) == 0 or min(sizes_post) == 0:
        print(f"kme-chaos: substreams pre={sizes_pre} "
              f"post={sizes_post} — empty group; raise --symbols",
              file=sys.stderr)
        return 2
    old_root = os.path.join(run_dir, "r0")
    new_root = os.path.join(run_dir, "r1")
    print(f"kme-chaos: scenario=reshard-under-storm seed={args.seed} "
          f"{n}->{m} groups split_at={split_at} pre={sizes_pre} "
          f"post={sizes_post} kill_after_legs={args.reshard_kill_legs}"
          f"\nkme-chaos: run dir {run_dir}", file=sys.stderr)

    def _serve_cmd(gdir: str, k: int, groups: int, port: int) -> list:
        serve_args = ["--engine", engine, "--compat", "fixed",
                      "--batch", str(args.batch),
                      "--slots", str(args.slots),
                      "--max-fills", str(args.max_fills),
                      "--checkpoint-every", str(args.checkpoint_every),
                      "--checkpoint-keep", str(args.checkpoint_keep),
                      "--group", f"{k}/{groups}",
                      "--listen", f"127.0.0.1:{port}",
                      "--idle-exit", str(args.idle_exit),
                      "--health-every", "0.1"]
        return [sys.executable, "-m", "kme_tpu.cli", "supervise",
                "--checkpoint-dir", gdir,
                "--stale-after", str(args.stale_after),
                "--stall-after", str(args.stall_after),
                "--max-restarts", str(args.max_restarts),
                "--grace", str(args.grace),
                "--backoff-base", "0.05", "--backoff-cap", "0.5",
                "--"] + serve_args

    env = dict(os.environ)
    env.pop("KME_FAULTS", None)     # the reshard itself is the attack
    env.pop("KME_FAULTS_STATE", None)
    env.setdefault("JAX_PLATFORMS", "cpu")

    # 10 Hz heartbeat sampling across BOTH generations: (wall time,
    # input offset) — the migration-pause evidence
    samples: dict = {("old", k): [] for k in range(n)}
    samples.update({("new", k): [] for k in range(m)})
    watch = ([("old", k, os.path.join(old_root, f"group{k}"))
              for k in range(n)]
             + [("new", k, os.path.join(new_root, f"group{k}"))
                for k in range(m)])
    stop_mon = threading.Event()

    def monitor() -> None:
        while not stop_mon.wait(0.1):
            for gen, k, gdir in watch:
                try:
                    with open(os.path.join(gdir, "serve.health")) as f:
                        hb = json.load(f)
                    samples[(gen, k)].append((time.time(),
                                              int(hb.get("offset", 0))))
                except (OSError, ValueError, TypeError):
                    pass

    mon = threading.Thread(target=monitor, daemon=True)
    mon.start()

    failures: List[str] = []
    t0 = time.time()

    def _wait_sups(sups: list, deadline: float) -> List[int]:
        while time.time() < deadline:
            if all(s.poll() is not None for s in sups):
                break
            time.sleep(0.25)
        for s in sups:
            if s.poll() is None:
                print("kme-chaos: TIMEOUT; killing a supervisor",
                      file=sys.stderr)
                s.kill()
                s.wait()
        return [s.returncode for s in sups]

    # -- phase A: the old generation serves the prefix, then drains ----
    sups_a, producers = [], []
    for k in range(n):
        gdir = os.path.join(old_root, f"group{k}")
        os.makedirs(gdir, exist_ok=True)
        port = _free_port()
        sups_a.append(subprocess.Popen(_serve_cmd(gdir, k, n, port),
                                       env=env))
        prod = _Producer("127.0.0.1", port, pre_sub[k],
                         topic=group_topics(k)[0],
                         topics=group_topics(k))
        prod.start()
        producers.append(prod)
    rcs_a = _wait_sups(sups_a, t0 + args.timeout)
    for prod in producers:
        prod.stop.set()
        prod.join(timeout=10.0)
    for k in range(n):
        if rcs_a[k] != 0:
            failures.append(f"old group {k} supervisor exited "
                            f"rc={rcs_a[k]}")
        if producers[k].sent < sizes_pre[k]:
            failures.append(f"old group {k} producer delivered "
                            f"{producers[k].sent} of {sizes_pre[k]}")
    t_drain = time.time()

    # -- the coordinator: one run SIGKILLed mid-settle, one to done ----
    coord_cmd = [sys.executable, "-m", "kme_tpu.bridge.reshard",
                 "--old-root", old_root, "--new-root", new_root,
                 "--old-groups", str(n), "--new-groups", str(m)]
    kenv = dict(env)
    kenv["KME_TEST_HOOKS"] = "1"
    t_coord0 = time.time()
    crash = subprocess.run(
        coord_cmd + ["--test-kill-after-legs",
                     str(args.reshard_kill_legs)],
        env=kenv, capture_output=True, text=True)
    if crash.returncode != -_signal.SIGKILL:
        failures.append(f"coordinator mid-settle SIGKILL never fired "
                        f"(rc={crash.returncode}); the crash-recovery "
                        f"leg proved nothing")
    rerun = subprocess.run(coord_cmd, env=env, capture_output=True,
                           text=True)
    t_coord1 = time.time()
    if rerun.returncode != 0:
        failures.append(f"coordinator re-run after the crash exited "
                        f"rc={rerun.returncode}: "
                        f"{rerun.stderr.strip()[-500:]}")
    jdoc: dict = {}
    try:
        with open(os.path.join(new_root, reshard_mod.JOURNAL)) as f:
            jdoc = json.load(f)
    except (OSError, ValueError) as e:
        failures.append(f"no readable reshard journal: {e}")
    legs = jdoc.get("migrate", {}).get("legs", [])
    settle = jdoc.get("settle", {})
    resume_cursors = settle.get("resume_cursors", [0] * m)
    if not jdoc.get("done"):
        failures.append("reshard journal never reached done")
    if jdoc.get("migrate", {}).get("old_offsets") != sizes_pre:
        failures.append(
            f"old generation drained at offsets "
            f"{jdoc.get('migrate', {}).get('old_offsets')} but the "
            f"substreams hold {sizes_pre} — the barrier leaked")
    if crash.returncode == -_signal.SIGKILL \
            and not settle.get("dup_suppressed"):
        failures.append("the settle re-run suppressed zero legs — the "
                        "pre-crash legs were lost, not deduped")

    # -- phase B: the new generation resumes the suffix over TCP ------
    ports_b = [_free_port() for _ in range(m)]
    sups_b = []
    for k in range(m):
        gdir = os.path.join(new_root, f"group{k}")
        os.makedirs(gdir, exist_ok=True)
        sups_b.append(subprocess.Popen(
            _serve_cmd(gdir, k, m, ports_b[k]), env=env))
    t_b = time.time()
    ready_deadline = t_b + args.timeout
    for k in range(m):
        ok = False
        while time.time() < ready_deadline:
            try:
                c = TcpBroker("127.0.0.1", ports_b[k], timeout=5.0)
                provision(c, topics=group_topics(k))   # idempotent
                c.close()
                ok = True
                break
            except (BrokerError, OSError):
                time.sleep(0.2)
        if not ok:
            failures.append(f"new group {k} broker never came up")
    links = front.FrontLinks(
        [f"127.0.0.1:{p}" for p in ports_b],
        cursors=resume_cursors, retries=40, backoff_s=0.1)
    fed = [0] * m
    feed_err: List[str] = []
    stop_feed = threading.Event()

    def feeder() -> None:
        # round-robin across the links so the groups drain
        # concurrently, one stamped produce per sweep per group
        idx = [0] * m
        left = sum(sizes_post)
        while left and not stop_feed.is_set():
            for g in range(m):
                if idx[g] >= len(post_sub[g]):
                    continue
                try:
                    links.send(g, post_sub[g][idx[g]])
                except Exception as e:      # noqa: BLE001 — report all
                    feed_err.append(f"link {g}: {e}")
                    return
                idx[g] += 1
                fed[g] += 1
                left -= 1

    fthread = threading.Thread(target=feeder, daemon=True)
    fthread.start()
    rcs_b = _wait_sups(sups_b, t_b + args.timeout)
    stop_feed.set()
    fthread.join(timeout=10.0)
    link_state = links.snapshot()
    links.close()
    stop_mon.set()
    mon.join(timeout=2.0)
    elapsed = time.time() - t0
    for k in range(m):
        if rcs_b[k] != 0:
            failures.append(f"new group {k} supervisor exited "
                            f"rc={rcs_b[k]}")
        if fed[k] < sizes_post[k]:
            failures.append(f"new group {k} front link delivered "
                            f"{fed[k]} of {sizes_post[k]}")
    failures.extend(feed_err)

    # -- durable logs: zero dup stamps, then byte parity --------------
    dup_stamps: dict = {}

    def _merged_actual(root: str, k: int, gen: str) -> List[str]:
        log_dir = os.path.join(root, f"group{k}", "broker-log")
        merged = []
        for topic in (group_topics(k)[1], group_topics(k)[2]):
            recs = read_matchout_records(log_dir, topic=topic)
            ring = DedupRing()
            keep = [r for r in recs
                    if not ring.is_dup(r.epoch, r.out_seq)]
            dup_stamps[f"{gen}:{topic}"] = ring.suppressed
            if ring.suppressed:
                failures.append(f"{ring.suppressed} duplicate "
                                f"(epoch,out_seq) stamp(s) in the "
                                f"{gen}-generation {topic} log")
            merged.extend(keep)
        merged.sort(key=lambda r: (r.out_seq
                                   if r.out_seq is not None else -1))
        return [f"{r.key} {r.value}" for r in merged]

    actual_pre = [_merged_actual(old_root, k, "old") for k in range(n)]
    actual_post = [_merged_actual(new_root, k, "new") for k in range(m)]
    # the new generation's MatchIn carries two stamp kinds on one shared
    # sequence space: coordinator legs at (epoch 1, 0..legs-1) and front
    # cursor stamps at (None, legs..) — out_seq alone must be unique
    for k in range(m):
        recs = read_matchout_records(
            os.path.join(new_root, f"group{k}", "broker-log"),
            topic=group_topics(k)[0])
        seqs = [r.out_seq for r in recs if r.out_seq is not None]
        dups = len(seqs) - len(set(seqs))
        dup_stamps[f"new:{group_topics(k)[0]}"] = dups
        if dups:
            failures.append(f"{dups} duplicate out_seq stamp(s) in the "
                            f"new-generation MatchIn.g{k} log")
    verify = front.verify_groups_reshard(
        lines, split_at, actual_pre, actual_post, compat="fixed",
        book_slots=args.slots, max_fills=args.max_fills,
        prefund=args.prefund)
    if not verify["ok"]:
        failures.append(f"reshard parity FAILED: "
                        f"{verify['mismatches'][:1]}")

    # -- the settlement ledger: exactly once, despite the SIGKILL -----
    legs_by_group = collections.Counter(leg[0] for leg in legs)
    ledger_checks = []
    for k in range(m):
        gdir = os.path.join(new_root, f"group{k}")
        matchin = collections.Counter(
            r.value for r in read_matchout_records(
                os.path.join(gdir, "broker-log"),
                topic=group_topics(k)[0]))
        for g, _seq, xid, _aid, _amt, leg_line in legs:
            if g != k:
                continue
            got = matchin.get(leg_line, 0)
            if got != 1:
                failures.append(f"settlement leg xid={xid} appears "
                                f"{got}x in MatchIn.g{k} (want exactly "
                                f"once)")
        eng, off = ck.load_oracle(gdir)
        pend = (ck.snapshot_extra(gdir, off).get("pending_reserve", {})
                if eng is not None else {})
        front_legs = sum(1 for ln in post_sub[k]
                         if front.is_internal_line(ln)
                         and parse_order(ln).action == op.TRANSFER)
        want_legs = legs_by_group.get(k, 0) + front_legs
        check = {"group": k, "coordinator_legs": legs_by_group.get(k, 0),
                 "front_legs": front_legs, "ledger": pend}
        ledger_checks.append(check)
        if eng is None:
            failures.append(f"new group {k} left no final snapshot")
        elif pend.get("legs") != want_legs or pend.get("rejected"):
            failures.append(
                f"new group {k} pending_reserve ledger {pend} != "
                f"{want_legs} settled legs with zero rejects")

    # -- the old epochs stay dead: durable re-fence probes ------------
    probes = [reshard_mod.probe_fenced(os.path.join(old_root,
                                                    f"group{k}"))
              for k in range(n)]
    for k, fenced in enumerate(probes):
        if not fenced:
            failures.append(f"old group {k} is NOT durably fenced — a "
                            f"zombie leader could dirty the retired "
                            f"log")

    # -- bounded dip: migration pause + the new generation's p99 ------
    first_new = [t for k in range(m)
                 for t, off in samples[("new", k)] if off >= 1]
    pause = (min(first_new) - t_drain) if first_new else None
    if pause is None:
        failures.append("the new generation never made progress")
    elif pause > args.reshard_pause:
        failures.append(f"migration pause {pause:.1f}s over the "
                        f"{args.reshard_pause}s bound")
    p99s: dict = {}
    for gen, count, root in (("old", n, old_root), ("new", m, new_root)):
        for k in range(count):
            try:
                with open(os.path.join(root, f"group{k}",
                                       "serve.health")) as f:
                    hb = json.load(f)
                p99s[f"{gen}:g{k}"] = hb.get("metrics", {}).get(
                    "latencies", {}).get("lat_e2e", {}).get("p99_ms")
            except (OSError, ValueError):
                p99s[f"{gen}:g{k}"] = None
    for k in range(m):
        p99 = p99s.get(f"new:g{k}")
        if p99 is None:
            failures.append(f"new group {k} left no lat_e2e p99 in its "
                            f"final heartbeat")
        elif p99 > args.reshard_p99_ms:
            # the new generation's histogram includes the settlement
            # legs, admitted before any leader was up — this bound
            # covers the migration gap, not just steady-state tail
            failures.append(f"SLO: new group {k} p99 {p99:.1f}ms over "
                            f"the {args.reshard_p99_ms}ms bound")

    # -- control-plane timeline: exactly-once phases + wall decompo- --
    # merge every event log the run left behind (old-generation
    # supervisors/serves under r0, coordinator + new generation under
    # r1) into one causally ordered timeline. The coordinator ran
    # TWICE (SIGKILLed mid-settle, then to done) against the same
    # phase-ordinal seqs — the merged timeline must hold each phase
    # EXACTLY once, or the replay-dedup discipline is broken.
    from kme_tpu.telemetry import events as cpevents

    timeline = cpevents.merge_logs([run_dir])
    merged_path = os.path.join(run_dir, "events.jsonl")
    try:
        cpevents.write_merged(timeline, merged_path)
    except OSError:
        merged_path = None
    phase_counts = {p: 0 for p in
                    reshard_mod.ReshardCoordinator.PHASES}
    migrate_off = None
    for ev in timeline:
        kind = str(ev.get("kind", ""))
        if ev.get("src") == "reshard" and kind.startswith("reshard."):
            p = kind.split(".", 1)[1]
            if p in phase_counts:
                phase_counts[p] += 1
            if p == "migrate":
                migrate_off = ev.get("off")
    if not timeline:
        failures.append("the run left no control-plane events — the "
                        "flight recorder never engaged")
    for p, c in phase_counts.items():
        if c != 1:
            failures.append(
                f"merged timeline holds {c} reshard.{p} event(s), "
                f"want exactly 1 — the post-SIGKILL re-run must dedup "
                f"its resumed phases, not duplicate (or drop) them")
    if sizes_pre and migrate_off != max(sizes_pre):
        failures.append(
            f"reshard.migrate offset anchor {migrate_off} != drained "
            f"high-water {max(sizes_pre)} — the timeline would merge "
            f"out of replay order")

    # reshard_pause_ms decomposed by phase: drain->coordinator gap and
    # post-coordinator relaunch measured by the drill's clock,
    # fence/migrate/settle by the coordinator's own (journal walls —
    # each recorded by whichever incarnation ran the phase, so they
    # survive the SIGKILL). Independent clocks, so the sum reconciles
    # against the measured pause within a tolerance that absorbs what
    # no phase owns: two interpreter spawns and the crashed settle
    # attempt.
    jwalls = jdoc.get("walls", {})
    walls_ms = {
        "drain": round(max(0.0, t_coord0 - t_drain) * 1000.0, 3),
        "fence": round(float(jwalls.get("fence_s", 0.0)) * 1000.0, 3),
        "migrate": round(float(jwalls.get("migrate_s", 0.0))
                         * 1000.0, 3),
        "settle": round(float(jwalls.get("settle_s", 0.0))
                        * 1000.0, 3),
        "relaunch": (round(max(0.0, min(first_new) - t_coord1)
                           * 1000.0, 3) if first_new else None),
    }
    for p in ("fence", "migrate", "settle"):
        if f"{p}_s" not in jwalls:
            failures.append(f"reshard journal carries no {p} wall — "
                            f"the pause cannot be attributed by phase")
    unattributed_ms = None
    if pause is not None and walls_ms["relaunch"] is not None:
        walls_sum = sum(v for v in walls_ms.values() if v is not None)
        unattributed_ms = round(pause * 1000.0 - walls_sum, 3)
        tol_ms = args.reshard_walls_tol * 1000.0
        if unattributed_ms < -500.0:
            failures.append(
                f"phase walls sum {walls_sum:.0f}ms EXCEEDS the "
                f"measured pause {pause * 1000.0:.0f}ms — a wall is "
                f"double-counted or a clock ran backwards")
        elif unattributed_ms > tol_ms:
            failures.append(
                f"phase walls account for {walls_sum:.0f}ms of the "
                f"{pause * 1000.0:.0f}ms pause — "
                f"{unattributed_ms:.0f}ms unattributed exceeds the "
                f"{tol_ms:.0f}ms tolerance")

    report = {
        "ok": not failures,
        "failures": failures,
        "scenario": "reshard-under-storm",
        "seed": args.seed,
        "events": len(msgs),
        "old_groups": n,
        "new_groups": m,
        "split_at": split_at,
        "substreams_pre": sizes_pre,
        "substreams_post": sizes_post,
        "elapsed_seconds": round(elapsed, 3),
        "reshard": reshard_info,
        "plan": jdoc.get("migrate", {}).get("plan"),
        "settle": {k: settle.get(k) for k in
                   ("legs", "dup_suppressed", "epochs",
                    "resume_cursors")},
        "coordinator_crash_rc": crash.returncode,
        "duplicate_stamps": dup_stamps,
        "ledger": ledger_checks,
        "old_fenced": probes,
        "migration_pause_s": (round(pause, 3)
                              if pause is not None else None),
        # flat perfgate-scrapeable gauges: reshard_pause_ms decomposed
        # by phase (perfgate.ADVISORY_METRICS — wall clocks, advisory)
        "reshard_pause_ms": (round(pause * 1000.0, 3)
                             if pause is not None else None),
        "reshard_drain_ms": walls_ms["drain"],
        "reshard_fence_ms": walls_ms["fence"],
        "reshard_migrate_ms": walls_ms["migrate"],
        "reshard_settle_ms": walls_ms["settle"],
        "reshard_relaunch_ms": walls_ms["relaunch"],
        "reshard_unattributed_ms": unattributed_ms,
        "timeline": {
            "count": len(timeline),
            "digest": cpevents.timeline_digest(timeline),
            "phase_counts": phase_counts,
            "merged_path": merged_path,
            "tail": [cpevents.format_event(ev)
                     for ev in timeline[-12:]],
        },
        "p99_ms": p99s,
        "front_links": link_state,
        "verify": dict(verify,
                       mismatches=verify.get("mismatches", [])[:3]),
        "run_dir": run_dir,
    }
    with open(report_path, "w") as f:
        json.dump(report, f, indent=1)
    status = "OK" if report["ok"] else "FAILED"
    print(f"kme-chaos: {status} — reshard-under-storm {n}->{m} "
          f"split_at={split_at} legs={settle.get('legs')} "
          f"settle_dedup={settle.get('dup_suppressed')} "
          f"crash_rc={crash.returncode} "
          f"dup_stamps={sum(dup_stamps.values())} "
          f"pause={report['migration_pause_s']}s "
          f"timeline={len(timeline)}ev "
          f"phases={[phase_counts[p] for p in sorted(phase_counts)]} "
          f"fenced={probes} "
          f"parity={'byte-exact' if verify['ok'] else 'DIVERGED'} "
          f"elapsed={elapsed:.1f}s", file=sys.stderr)
    for fail in failures:
        print(f"kme-chaos: FAIL: {fail}", file=sys.stderr)
    print(f"kme-chaos: report written to {report_path}",
          file=sys.stderr)
    return 0 if report["ok"] else 1


def scenario_registry() -> dict:
    """name -> one-line description for every runnable scenario: the
    four recovery drills plus the five adversarial storm profiles
    (workload.STORM_PROFILES). `kme-chaos --list-scenarios` prints it."""
    from kme_tpu.workload import STORM_PROFILES

    reg = {
        "default": "at-least-once recovery gauntlet: every fault class "
                   "(transport, snapshot, journal, kill, stall), "
                   "verify_stream prefix+replay composition",
        "failover": "hot-standby promotion under exactly-once: SIGKILL "
                    "the leader mid-stream, bounded promotion, epoch "
                    "fencing, deduped stream byte-exact",
        "shard-failover": "multi-leader drill: kill the busiest "
                          "group's leader; survivors must not dip, "
                          "merged stream byte-exact, zero duplicate "
                          "stamps",
        "feed-failover": "market-data drill: kill the leader with "
                         "live feed subscribers; books byte-exact "
                         "post-promotion, zero dup/missing delta "
                         "seqs",
        "reshard-under-storm": "live N->M re-split mid-flash-crowd: "
                               "drain at a batch barrier, fence + "
                               "migrate + settle (coordinator "
                               "SIGKILLed mid-settle and re-run), new "
                               "generation resumes over TCP front "
                               "links; byte parity across both "
                               "topologies, zero dup stamps, "
                               "exactly-once settlement, bounded "
                               "pause",
    }
    for name, prof in STORM_PROFILES.items():
        reg[name] = (f"storm: {prof.summary} (adaptive overload "
                     f"control, oracle parity over the admitted "
                     f"stream, SLO verdict)")
    return reg


class _StormProducer(threading.Thread):
    """Per-record MatchIn feeder for the storm scenarios. Unlike
    _Producer it does NOT retry a shed record: the adaptive controller's
    rej_overload means the record was rejected at admission, and
    shedding must act as a pure input filter — the dropped record simply
    never existed as far as the engine (and the oracle replay of the
    admitted stream) is concerned. The producer honors the AIMD backoff
    hint carried on the reject and classifies every offer/shed by
    priority class for the fairness verdict."""

    def __init__(self, host: str, port: int, lines: List[str],
                 windows: List[Tuple[int, int, int]],
                 pace_s: float) -> None:
        super().__init__(daemon=True)
        self.host, self.port = host, port
        self.lines, self.windows, self.pace_s = lines, windows, pace_s
        self.offered = 0
        self.sheds = 0
        self.reconnects = 0
        self.backoff_slept_ms = 0.0
        self.offered_by_class = {0: 0, 1: 0, 2: 0}
        self.shed_by_class = {0: 0, 1: 0, 2: 0}
        self.stop = threading.Event()

    def run(self) -> None:
        from kme_tpu.bridge.broker import (BrokerError, BrokerOverload,
                                           classify_produce)
        from kme_tpu.bridge.provision import provision
        from kme_tpu.bridge.tcp import TcpBroker

        client = None
        i = 0
        while i < len(self.lines) and not self.stop.is_set():
            cls, _, _ = classify_produce(self.lines[i])
            burst = any(lo <= i < hi for lo, hi, _ in self.windows)
            try:
                if client is None:
                    client = TcpBroker(self.host, self.port,
                                       timeout=10.0)
                    provision(client)           # idempotent
                client.produce(TOPIC_IN, None, self.lines[i])
                self.offered += 1
                self.offered_by_class[cls] += 1
                i += 1
                # rate lives in producer pacing: flat-out inside a
                # burst window, paced in the steady state
                if not burst and self.pace_s > 0:
                    time.sleep(self.pace_s)
            except BrokerOverload as e:
                self.offered += 1
                self.offered_by_class[cls] += 1
                self.sheds += 1
                self.shed_by_class[cls] += 1
                i += 1                          # dropped, not retried
                hint = getattr(e, "backoff_ms", None)
                if hint:
                    nap = min(int(hint), 100) / 1e3
                    self.backoff_slept_ms += nap * 1e3
                    time.sleep(nap)
            except (BrokerError, OSError):
                # serve still coming up, or a transient transport blip:
                # reconnect and retry the SAME record (no faults are
                # injected in a storm run, so ambiguity is startup-only)
                if client is not None:
                    try:
                        client.close()
                    except OSError:
                        pass
                client = None
                self.reconnects += 1
                time.sleep(0.2)
        if client is not None:
            try:
                client.close()
            except OSError:
                pass


def run_storm(args, run_dir: str, report_path: str) -> int:
    """--scenario <storm-name>: drive one adversarial storm profile
    (workload.STORM_PROFILES) at a supervise-free kme-serve running the
    adaptive overload controller, then prove graceful degradation:

    - ORACLE PARITY over the admitted stream: the durable MatchIn log
      IS the admitted sequence (everything the controller let through);
      an in-process oracle replay of exactly that sequence must match
      the deduped durable MatchOut BYTE-EXACTLY, with ZERO duplicate
      (epoch, out_seq) stamps — shedding is a pure input filter, never
      a corruption;
    - SLO VERDICT: the final heartbeat's lat_e2e p99 (broker admission
      -> outputs visible) must sit under --storm-p99-ms, and admitted
      throughput must clear --storm-min-tput records/s;
    - PRIORITY FAIRNESS: when anything shed, book-shrinking traffic
      (cancels/payouts, class 0) must shed at a strictly lower rate
      than new orders (class 2) — the whole point of priority-aware
      admission;
    - at least --min-sheds records actually shed (a storm that never
      pushed the controller proves nothing).
    """
    from kme_tpu.bridge.consume import DedupRing
    from kme_tpu.wire import dumps_order
    from kme_tpu.workload import (STORM_PROFILES, storm_stream,
                                  storm_windows)

    prof = STORM_PROFILES[args.scenario]
    symbols = args.storm_symbols or prof.symbols
    accounts = args.storm_accounts or prof.accounts
    msgs = storm_stream(args.scenario, args.events,
                        num_symbols=symbols, num_accounts=accounts,
                        seed=args.seed)
    lines = [dumps_order(m) for m in msgs]
    windows = storm_windows(args.scenario, args.events,
                            num_symbols=symbols, num_accounts=accounts)
    ckpt_dir = os.path.join(run_dir, "state")
    os.makedirs(ckpt_dir, exist_ok=True)
    health = os.path.join(ckpt_dir, "serve.health")
    log_dir = os.path.join(ckpt_dir, "broker-log")
    port = _free_port()
    print(f"kme-chaos: scenario={args.scenario} seed={args.seed} "
          f"events={args.events} symbols={symbols} accounts={accounts} "
          f"records={len(lines)} windows={windows} "
          f"high_lag={args.overload_high_lag}\n"
          f"kme-chaos: run dir {run_dir}", file=sys.stderr)

    serve_cmd = [sys.executable, "-m", "kme_tpu.cli", "serve",
                 "--engine", args.engine, "--compat", "fixed",
                 "--batch", str(args.batch),
                 "--slots", str(args.slots),
                 "--max-fills", str(args.max_fills),
                 "--symbols", str(max(symbols, 8)),
                 "--accounts", str(max(accounts + 8, 128)),
                 "--checkpoint-dir", ckpt_dir,
                 "--checkpoint-every", str(args.checkpoint_every),
                 "--overload-high-lag", str(args.overload_high_lag),
                 "--listen", f"127.0.0.1:{port}",
                 "--idle-exit", str(args.idle_exit),
                 "--health-file", health,
                 "--health-every", "0.1"]
    if not args.no_journal:
        serve_cmd += ["--journal-out",
                      os.path.join(run_dir, "journal.jsonl")]
    env = dict(os.environ)
    env.pop("KME_FAULTS", None)     # the storm itself is the attack
    env.pop("KME_FAULTS_STATE", None)
    env.setdefault("JAX_PLATFORMS", "cpu")
    t0 = time.time()
    srv = subprocess.Popen(serve_cmd, env=env)
    producer = _StormProducer("127.0.0.1", port, lines, windows,
                              pace_s=args.pace_ms / 1e3)
    producer.start()

    rc: Optional[int] = None
    deadline = t0 + args.timeout
    while time.time() < deadline:
        rc = srv.poll()
        if rc is not None:
            break
        time.sleep(0.25)
    if rc is None:
        print(f"kme-chaos: TIMEOUT after {args.timeout}s; killing "
              f"kme-serve", file=sys.stderr)
        srv.kill()
        srv.wait()
        rc = srv.returncode
    producer.stop.set()
    producer.join(timeout=10.0)
    elapsed = time.time() - t0

    failures: List[str] = []
    if rc != 0:
        failures.append(f"kme-serve exited rc={rc}")
    if producer.offered < len(lines):
        failures.append(f"producer only offered {producer.offered} of "
                        f"{len(lines)} records")

    # oracle parity over the ADMITTED stream: the durable MatchIn log
    # is ground truth for what got past the controller
    admitted_lines = [r.value for r in
                      read_matchout_records(log_dir, topic=TOPIC_IN)]
    per_msg = expected_groups(admitted_lines, args.slots,
                              args.max_fills)
    flat = [ln for g in per_msg for ln in g]
    out_recs = read_matchout_records(log_dir)
    ring = DedupRing()
    visible = [f"{r.key} {r.value}" for r in out_recs
               if not ring.is_dup(r.epoch, r.out_seq)]
    parity = {"admitted_records": len(admitted_lines),
              "got_lines": len(visible),
              "expected_lines": len(flat),
              "duplicate_stamps": ring.suppressed}
    if ring.suppressed:
        failures.append(f"{ring.suppressed} duplicate (epoch,out_seq) "
                        f"stamp(s) in the durable MatchOut log")
    if visible != flat:
        n = min(len(visible), len(flat))
        div = next((k for k in range(n) if visible[k] != flat[k]), n)
        parity["error"] = (f"admitted-stream replay diverges at line "
                           f"{div} (got {len(visible)}, want "
                           f"{len(flat)})")
        failures.append(f"oracle parity over the admitted stream "
                        f"FAILED: {parity['error']}")

    # shed accounting + priority fairness (producer-side ground truth)
    shed = producer.sheds
    shed_frac = shed / max(1, producer.offered)
    if shed < args.min_sheds:
        failures.append(f"only {shed} record(s) shed; the storm never "
                        f"pushed the controller (need >= "
                        f"{args.min_sheds})")

    def _rate(cls: int) -> Optional[float]:
        n = producer.offered_by_class[cls]
        return producer.shed_by_class[cls] / n if n else None

    rates = {cls: _rate(cls) for cls in (0, 1, 2)}
    if shed and producer.offered_by_class[0] \
            and rates[2] is not None:
        if rates[0] is None or rates[0] >= rates[2]:
            failures.append(
                f"priority inversion: class-0 (cancel/payout) shed "
                f"rate {rates[0]} is not strictly below class-2 (new "
                f"order) shed rate {rates[2]}")

    # SLO verdict from the final heartbeat
    slo: dict = {"p99_bound_ms": args.storm_p99_ms,
                 "min_tput": args.storm_min_tput}
    gauges: dict = {}
    try:
        with open(health) as f:
            hb = json.load(f)
        met = hb.get("metrics", {})
        gauges = met.get("gauges", {})
        slo["p99_ms"] = met.get("latencies", {}).get(
            "lat_e2e", {}).get("p99_ms")
    except (OSError, ValueError):
        slo["p99_ms"] = None
    admitted = producer.offered - shed
    slo["tput"] = round(admitted / elapsed, 1) if elapsed > 0 else None
    if slo["p99_ms"] is None:
        failures.append("no lat_e2e p99 in the final heartbeat")
    elif slo["p99_ms"] > args.storm_p99_ms:
        failures.append(f"SLO: p99 admission-to-produce "
                        f"{slo['p99_ms']:.1f}ms over the "
                        f"{args.storm_p99_ms}ms bound")
    if slo["tput"] is not None and slo["tput"] < args.storm_min_tput:
        failures.append(f"SLO: survivor throughput {slo['tput']}/s "
                        f"under the {args.storm_min_tput}/s floor")
    slo["ok"] = not any(f.startswith("SLO:") for f in failures)

    report = {
        "ok": not failures,
        "failures": failures,
        "scenario": args.scenario,
        "summary": prof.summary,
        "seed": args.seed,
        "events": args.events,
        "symbols": symbols,
        "accounts": accounts,
        "records": len(lines),
        "windows": [list(w) for w in windows],
        "elapsed_seconds": round(elapsed, 3),
        "offered": producer.offered,
        "admitted": admitted,
        "shed": shed,
        "shed_frac": round(shed_frac, 4),
        "offered_by_class": producer.offered_by_class,
        "shed_by_class": producer.shed_by_class,
        "shed_rates_by_class": {str(k): (round(v, 4)
                                         if v is not None else None)
                                for k, v in rates.items()},
        "backoff_slept_ms": round(producer.backoff_slept_ms, 1),
        "reconnects": producer.reconnects,
        "slo": slo,
        "parity": parity,
        "controller_gauges": {k: v for k, v in gauges.items()
                              if k.startswith("overload_")
                              or k.startswith("shed_by_class")
                              or k.startswith("admitted_by_class")},
        "timeline": _timeline_section(run_dir),
        "run_dir": run_dir,
    }
    with open(report_path, "w") as f:
        json.dump(report, f, indent=1)
    status = "OK" if report["ok"] else "FAILED"
    print(f"kme-chaos: {status} — {args.scenario}: offered "
          f"{producer.offered}, shed {shed} ({shed_frac:.1%}), "
          f"rates by class {report['shed_rates_by_class']}, "
          f"p99={slo['p99_ms']}ms (bound {args.storm_p99_ms}ms), "
          f"tput={slo['tput']}/s, parity="
          f"{'byte-exact' if 'error' not in parity else 'DIVERGED'}, "
          f"dup_stamps={ring.suppressed}, elapsed={elapsed:.1f}s",
          file=sys.stderr)
    for fail in failures:
        print(f"kme-chaos: FAIL: {fail}", file=sys.stderr)
    print(f"kme-chaos: report written to {report_path}",
          file=sys.stderr)
    return 0 if report["ok"] else 1


def _fault_fires(state_dir: str) -> dict:
    fires = {}
    try:
        for name in sorted(os.listdir(state_dir)):
            if name.endswith(".fired"):
                with open(os.path.join(state_dir, name)) as f:
                    fires[name[:-len(".fired")]] = int(f.read().strip()
                                                       or 0)
    except (OSError, ValueError):
        pass
    return fires


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="kme-chaos", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--seed", type=int, default=0,
                   help="seeds the workload AND every fault rule")
    from kme_tpu.workload import STORM_PROFILES

    p.add_argument("--list-scenarios", action="store_true",
                   help="print the scenario registry (name + one-line "
                        "description) and exit")
    p.add_argument("--scenario",
                   choices=("default", "failover", "shard-failover",
                            "feed-failover", "reshard-under-storm")
                   + tuple(STORM_PROFILES),
                   default="default",
                   help="default = the at-least-once recovery gauntlet "
                        "(every fault class, verify_stream); failover "
                        "= hot-standby promotion under exactly-once: "
                        "SIGKILL the leader mid-stream, require the "
                        "supervisor to promote the replica with a "
                        "higher epoch within --max-failover seconds, "
                        "the old epoch to be fenced, and the deduped "
                        "MatchOut stream to be byte-exact with ZERO "
                        "visible duplicates; shard-failover = the "
                        "multi-leader drill: --groups shard groups "
                        "serve concurrently, the busiest group's "
                        "leader is SIGKILLed mid-substream, survivors "
                        "must not dip >=10%, the standby must promote "
                        "within --max-failover, the merged stream "
                        "must be byte-exact and no durable log may "
                        "hold a duplicate (epoch,out_seq) stamp; any "
                        "storm-profile name (--list-scenarios) = drive "
                        "that adversarial workload at the adaptive "
                        "overload controller and verify oracle parity "
                        "over the admitted stream, priority fairness "
                        "and the SLO verdict")
    p.add_argument("--groups", type=int, default=2,
                   help="shard-failover scenario: number of shard "
                        "groups (leader pairs)")
    p.add_argument("--prefund", type=int, default=8,
                   help="shard-failover scenario: chunked reserve "
                        "grant size for cross-shard transfers "
                        "(kme-front --prefund)")
    p.add_argument("--cross-frac", type=float, default=0.5,
                   help="shard-failover scenario: fraction of orders "
                        "placed from non-home accounts (the "
                        "cross-account workload profile)")
    p.add_argument("--groups-to", type=int, default=4, metavar="M",
                   help="reshard-under-storm scenario: the new group "
                        "count the coordinator re-splits to "
                        "mid-stream")
    p.add_argument("--reshard-kill-legs", type=int, default=5,
                   metavar="J",
                   help="reshard-under-storm scenario: SIGKILL the "
                        "coordinator after J settlement legs (the "
                        "crash-during-migration fault; the re-run "
                        "must dedup)")
    p.add_argument("--reshard-pause", type=float, default=90.0,
                   help="reshard-under-storm scenario: bound on the "
                        "migration pause, old-generation drain -> "
                        "first new-generation progress (seconds)")
    p.add_argument("--reshard-walls-tol", type=float, default=20.0,
                   help="reshard-under-storm scenario: tolerance "
                        "(seconds) for the pause left unattributed "
                        "after the per-phase walls (drain/fence/"
                        "migrate/settle/relaunch) are summed — covers "
                        "the two coordinator interpreter spawns and "
                        "the crashed settle attempt, which no phase "
                        "owns")
    p.add_argument("--reshard-p99-ms", type=float, default=10_000.0,
                   help="reshard-under-storm scenario: bound on the "
                        "new generation's final lat_e2e p99. The "
                        "coordinator's settlement legs are admitted "
                        "while no leader is up, so their e2e latency "
                        "IS the migration gap — this bounds the "
                        "user-visible worst case across the re-split, "
                        "not steady-state tail latency")
    p.add_argument("--max-failover", type=float, default=2.0,
                   help="failover scenario: max seconds from failure "
                        "detection to the promoted replica serving")
    p.add_argument("--storm-symbols", type=int, default=None,
                   help="storm scenarios: override the profile's "
                        "symbol-universe width (reduced-scale CI runs)")
    p.add_argument("--storm-accounts", type=int, default=None,
                   help="storm scenarios: override the profile's "
                        "account count")
    p.add_argument("--storm-p99-ms", type=float, default=2000.0,
                   help="storm scenarios: SLO bound on the lat_e2e p99 "
                        "(broker admission -> outputs visible)")
    p.add_argument("--storm-min-tput", type=float, default=10.0,
                   help="storm scenarios: survivor throughput floor "
                        "(admitted records/s over the whole run)")
    p.add_argument("--min-sheds", type=int, default=1,
                   help="storm scenarios: fail unless at least this "
                        "many records were shed (a storm that never "
                        "pushed the controller proves nothing)")
    p.add_argument("--pace-ms", type=float, default=1.0,
                   help="storm scenarios: per-record producer pacing "
                        "OUTSIDE burst windows (inside a window the "
                        "producer runs flat out — that asymmetry IS "
                        "the storm's rate multiplier)")
    p.add_argument("--overload-high-lag", type=int, default=48,
                   help="storm scenarios: the adaptive controller's "
                        "shedding threshold passed to kme-serve")
    p.add_argument("--events", type=int, default=2000)
    p.add_argument("--accounts", type=int, default=10)
    p.add_argument("--symbols", type=int, default=3)
    p.add_argument("--engine", choices=("oracle", "native", "seq",
                                        "lanes"), default="oracle",
                   help="serving engine under attack (oracle is host-"
                        "only and fast on CPU; the recovery machinery "
                        "under test is engine-independent)")
    p.add_argument("--batch", type=int, default=64)
    p.add_argument("--slots", type=int, default=128)
    p.add_argument("--max-fills", type=int, default=32)
    p.add_argument("--checkpoint-every", type=int, default=60)
    p.add_argument("--checkpoint-keep", type=int, default=3)
    p.add_argument("--schedule", default=None, metavar="SPEC",
                   help="KME_FAULTS spec (default: a seed-derived "
                        "schedule covering transport, snapshot, "
                        "journal, kill and stall faults)")
    p.add_argument("--dir", default=None, metavar="DIR",
                   help="run directory (checkpoints, broker logs, "
                        "journal, report); default: a temp dir, kept "
                        "on failure")
    p.add_argument("--max-lag", type=int, default=None,
                   help="bounded-ingress backlog bound passed to "
                        "kme-serve (producer treats rej_overload as "
                        "backpressure)")
    p.add_argument("--max-restarts", type=int, default=10)
    p.add_argument("--min-restarts", type=int, default=1,
                   help="fail unless at least this many automatic "
                        "restarts happened (a chaos run where nothing "
                        "died proves nothing)")
    p.add_argument("--stale-after", type=float, default=5.0)
    p.add_argument("--stall-after", type=float, default=2.5)
    p.add_argument("--grace", type=float, default=30.0)
    p.add_argument("--idle-exit", type=float, default=5.0)
    p.add_argument("--timeout", type=float, default=300.0,
                   help="overall wall-clock budget for the supervised "
                        "run")
    p.add_argument("--no-journal", action="store_true",
                   help="skip the flight recorder (and the journal.torn "
                        "fault)")
    p.add_argument("--report", default=None, metavar="PATH",
                   help="write the JSON report here (default: "
                        "<dir>/chaos-report.json)")
    args = p.parse_args(argv)

    if args.list_scenarios:
        reg = scenario_registry()
        width = max(len(n) for n in reg)
        for name, desc in reg.items():
            print(f"{name:<{width}}  {desc}")
        return 0

    from kme_tpu.wire import dumps_order
    from kme_tpu.workload import harness_stream

    failover = args.scenario == "failover"
    run_dir = args.dir
    if run_dir is None:
        import tempfile

        run_dir = tempfile.mkdtemp(prefix="kme-chaos-")
    os.makedirs(run_dir, exist_ok=True)
    if args.scenario == "shard-failover":
        report_path = args.report or os.path.join(
            run_dir, "chaos-report.json")
        return run_shard_failover(args, run_dir, report_path)
    if args.scenario == "feed-failover":
        report_path = args.report or os.path.join(
            run_dir, "chaos-report.json")
        return run_feed_failover(args, run_dir, report_path)
    if args.scenario == "reshard-under-storm":
        report_path = args.report or os.path.join(
            run_dir, "chaos-report.json")
        return run_reshard_storm(args, run_dir, report_path)
    if args.scenario in STORM_PROFILES:
        report_path = args.report or os.path.join(
            run_dir, "chaos-report.json")
        return run_storm(args, run_dir, report_path)
    ckpt_dir = os.path.join(run_dir, "state")
    state_dir = os.path.join(run_dir, "fault-state")
    os.makedirs(ckpt_dir, exist_ok=True)
    journal = (None if args.no_journal or failover
               else os.path.join(run_dir, "journal.jsonl"))
    schedule = args.schedule
    if schedule is None:
        schedule = (failover_schedule(args.seed, args.events) if failover
                    else default_schedule(args.seed, args.events,
                                          journal is not None))
    report_path = args.report or os.path.join(run_dir,
                                              "chaos-report.json")

    print(f"kme-chaos: scenario={args.scenario} seed={args.seed} "
          f"events={args.events} "
          f"engine={args.engine}\nkme-chaos: schedule {schedule}\n"
          f"kme-chaos: run dir {run_dir}", file=sys.stderr)

    # 1. the ground truth (in-process; no faults are active here)
    msgs = harness_stream(args.events, seed=args.seed,
                          num_accounts=args.accounts,
                          num_symbols=args.symbols,
                          payout_opcode_bug=False, validate=True)
    lines = [dumps_order(m) for m in msgs]
    per_msg = expected_groups(lines, args.slots, args.max_fills)

    # 2. the supervised service under attack
    port = _free_port()
    serve_args = ["--engine", args.engine, "--compat", "fixed",
                  "--batch", str(args.batch),
                  "--slots", str(args.slots),
                  "--max-fills", str(args.max_fills),
                  "--checkpoint-every", str(args.checkpoint_every),
                  "--checkpoint-keep", str(args.checkpoint_keep),
                  "--listen", f"127.0.0.1:{port}",
                  "--idle-exit", str(args.idle_exit),
                  "--health-every", "0.2"]
    if args.max_lag is not None:
        serve_args += ["--max-lag", str(args.max_lag)]
    if journal is not None:
        serve_args += ["--journal-out", journal]
    sup_cmd = [sys.executable, "-m", "kme_tpu.cli", "supervise",
               "--checkpoint-dir", ckpt_dir,
               "--stale-after", str(args.stale_after),
               "--stall-after", str(args.stall_after),
               "--max-restarts", str(args.max_restarts),
               "--grace", str(args.grace),
               "--backoff-base", "0.05", "--backoff-cap", "0.5"]
    if failover:
        # hot standby + a tight watch poll: the failover bound starts
        # at failure DETECTION, but a slow detector makes for a slow
        # drill
        sup_cmd += ["--standby", "--poll", "0.1"]
    sup_cmd += ["--"] + serve_args
    env = dict(os.environ)
    env["KME_FAULTS"] = schedule
    env["KME_FAULTS_STATE"] = state_dir
    env.setdefault("JAX_PLATFORMS", "cpu")
    t0 = time.time()
    sup = subprocess.Popen(sup_cmd, env=env)

    # 3. feed the input (idempotent; concurrent with the attack)
    producer = _Producer("127.0.0.1", port, lines)
    producer.start()

    # 4. wait for the run to finish
    sup_rc: Optional[int] = None
    deadline = t0 + args.timeout
    while time.time() < deadline:
        sup_rc = sup.poll()
        if sup_rc is not None:
            break
        time.sleep(0.25)
    if sup_rc is None:
        print(f"kme-chaos: TIMEOUT after {args.timeout}s; killing the "
              f"supervisor", file=sys.stderr)
        sup.kill()
        sup.wait()
    producer.stop.set()
    producer.join(timeout=10.0)
    elapsed = time.time() - t0

    # 5. post-mortem verification against the oracle
    log_dir = os.path.join(ckpt_dir, "broker-log")
    recs = read_matchout_records(log_dir)
    got = [f"{r.key} {r.value}" for r in recs]
    if failover:
        ok, verify = verify_failover(recs, per_msg)
    else:
        ok, verify = verify_stream(got, per_msg)

    sup_state = {}
    try:
        with open(os.path.join(ckpt_dir, "supervisor.json")) as f:
            sup_state = json.load(f)
    except (OSError, ValueError):
        pass
    restarts = int(sup_state.get("restarts_total", 0))
    recoveries = sup_state.get("recoveries", [])
    rec_times = [r["recovered_in"] for r in recoveries
                 if "recovered_in" in r]

    failures = []
    if sup_rc != 0:
        failures.append(f"supervisor exited rc={sup_rc}")
    if not ok:
        failures.append(f"stream verification failed: "
                        f"{verify.get('error')}")
    if producer.sent < len(lines):
        failures.append(f"producer only delivered {producer.sent} of "
                        f"{len(lines)} records")
    if restarts < args.min_restarts:
        failures.append(f"only {restarts} automatic restart(s); "
                        f"need >= {args.min_restarts}")

    failover_report = None
    if failover:
        failover_report = _check_failover(
            ckpt_dir, log_dir, recoveries, args.max_failover, failures)

    report = {
        "ok": not failures,
        "failures": failures,
        "scenario": args.scenario,
        "failover": failover_report,
        "seed": args.seed,
        "events": args.events,
        "engine": args.engine,
        "schedule": schedule,
        "elapsed_seconds": round(elapsed, 3),
        "verify": verify,
        "restarts_total": restarts,
        "recovery_seconds": rec_times,
        "recovery_seconds_max": max(rec_times) if rec_times else None,
        "supervisor": sup_state,
        "fault_fires": _fault_fires(state_dir),
        "producer": {"sent": producer.sent,
                     "overload_retries": producer.overload_retries,
                     "reconnects": producer.reconnects},
        "timeline": _timeline_section(run_dir),
        "run_dir": run_dir,
    }
    with open(report_path, "w") as f:
        json.dump(report, f, indent=1)
    status = "OK" if report["ok"] else "FAILED"
    if failover_report is not None:
        print(f"kme-chaos: failover — promotions="
              f"{failover_report.get('promotions')} "
              f"failover_seconds={failover_report.get('failover_seconds')} "
              f"dup_suppressed={failover_report.get('dup_suppressed_total')} "
              f"leader_epoch={failover_report.get('leader_epoch')} "
              f"stale_epoch_fenced="
              f"{failover_report.get('stale_epoch_fenced')}",
              file=sys.stderr)
    print(f"kme-chaos: {status} — {len(got)} MatchOut lines verified "
          f"against {len(per_msg)} oracle groups "
          f"(replays={verify.get('replays', '?')}, replayed_messages="
          f"{verify.get('replayed_messages', '?')}), "
          f"restarts={restarts}, "
          f"recovery={rec_times and max(rec_times) or 'n/a'}s, "
          f"elapsed={elapsed:.1f}s", file=sys.stderr)
    for fail in failures:
        print(f"kme-chaos: FAIL: {fail}", file=sys.stderr)
    print(f"kme-chaos: report written to {report_path}", file=sys.stderr)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
