"""Engine service — the KProcessor.main role: host the broker endpoint
and pump MatchIn -> engine -> MatchOut.

The reference splits broker (external Kafka) from engine (JVM); here
`kme-serve` hosts both: it listens on --listen for the bridge's TCP
broker protocol (provisioner / load generator / consumer connect there)
and runs the MatchService poll loop in the foreground. Use
--auto-provision to create the topics at startup (else run
kme-provision first, as the reference README orders it)."""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="kme-serve", description=__doc__)
    p.add_argument("--listen", default="127.0.0.1:9092", metavar="HOST:PORT")
    p.add_argument("--kafka", default=None, metavar="BOOTSTRAP",
                   help="serve against a REAL Kafka cluster through the "
                        "aiokafka transport (bridge/kafka.py) instead of "
                        "hosting the in-process broker: topics/offsets "
                        "live in Kafka (durable there), --listen/--log-dir "
                        "are ignored, and the reference's unmodified Node "
                        "harness can drive the engine")
    p.add_argument("--engine", choices=("seq", "lanes", "oracle",
                                        "native"),
                   default="seq",
                   help="seq = sequential Pallas mega-kernel (fixed "
                        "mode, the flagship); lanes = vectorized sweep "
                        "engine (fixed mode, shardable); native = C++ "
                        "quirk-exact engine (fast java compat); oracle "
                        "= Python reference replica")
    p.add_argument("--compat", choices=("java", "fixed"), default="fixed")
    p.add_argument("--batch", type=int, default=1024,
                   help="max records per engine micro-batch")
    p.add_argument("--symbols", type=int, default=1024)
    p.add_argument("--accounts", type=int, default=4096)
    p.add_argument("--slots", type=int, default=128)
    p.add_argument("--max-fills", type=int, default=16)
    p.add_argument("--width", type=int, default=8)
    p.add_argument("--shards", type=int, default=1)
    p.add_argument("--strict", action="store_true",
                   help="die on malformed input records like the "
                        "reference's serde does (KProcessor.java:513-517)")
    p.add_argument("--checkpoint-dir", default=None, metavar="DIR",
                   help="snapshot engine state + input offset here at "
                        "batch boundaries; resume from the newest valid "
                        "snapshot at startup (at-least-once replay)")
    p.add_argument("--checkpoint-every", type=int, default=4096,
                   metavar="N", help="records between snapshots")
    p.add_argument("--checkpoint-keep", type=int, default=None,
                   metavar="N",
                   help="snapshots retained per kind (default 3, or "
                        "KME_CKPT_KEEP); deeper retention survives "
                        "multi-snapshot corruption (load falls back "
                        "newest -> older on digest/parse failure)")
    p.add_argument("--max-lag", type=int, default=None, metavar="N",
                   help="bounded ingress: reject produces to MatchIn "
                        "with a wire-level rej_overload once the "
                        "unconsumed backlog reaches N records (shed "
                        "load instead of stalling); in-process broker "
                        "only")
    p.add_argument("--overload-high-lag", type=int, default=None,
                   metavar="N",
                   help="adaptive overload control: instead of the "
                        "binary --max-lag shed, run the normal -> "
                        "shedding -> draining degradation state machine "
                        "with priority-aware admission (cancels/payouts "
                        "pass while new orders shed, per-account "
                        "fairness caps) once the MatchIn backlog "
                        "reaches N; in-process broker only")
    p.add_argument("--overload-low-lag", type=int, default=None,
                   metavar="N",
                   help="hysteresis low-water mark: leave shedding once "
                        "the backlog falls to N (default high/2)")
    p.add_argument("--overload-drain-lag", type=int, default=None,
                   metavar="N",
                   help="draining high-water mark: admit ONLY book-"
                        "shrinking traffic (cancel/payout/remove) past "
                        "N (default 2*high)")
    p.add_argument("--overload-p99-ms", type=float, default=None,
                   metavar="MS",
                   help="also enter shedding when the admission-to-"
                        "produce latency EWMA exceeds MS ms, even "
                        "below the backlog threshold")
    p.add_argument("--overload-account-cap", type=float, default=0.5,
                   metavar="FRAC",
                   help="per-account fairness cap: shed an account's "
                        "new orders while it holds more than FRAC of "
                        "the recent admitted-order window (default 0.5)")
    p.add_argument("--log-dir", default=None, metavar="DIR",
                   help="persist topic logs here (append-only JSONL) so "
                        "the broker survives restarts; defaults to "
                        "<checkpoint-dir>/broker-log when checkpointing "
                        "is on — the restored input offset must address "
                        "the same MatchIn records after a restart")
    p.add_argument("--auto-provision", action="store_true")
    p.add_argument("--max-messages", type=int, default=None)
    p.add_argument("--idle-exit", type=float, default=None, metavar="SECS")
    p.add_argument("--health-file", default=None, metavar="PATH",
                   help="write a {pid, time, seen, offset} heartbeat JSON "
                        "here (atomic replace) every --health-every "
                        "seconds; kme-supervise watches its mtime")
    p.add_argument("--health-every", type=float, default=1.0)
    p.add_argument("--metrics-port", type=int, default=None, metavar="PORT",
                   help="serve Prometheus text exposition on "
                        "http://0.0.0.0:PORT/metrics (and JSON on "
                        "/metrics.json) while the service runs; 0 picks "
                        "a free port (printed to stderr)")
    p.add_argument("--trace-out", default=None, metavar="PATH",
                   help="write a Chrome trace-event JSON (chrome://"
                        "tracing / Perfetto) of the engine phase "
                        "timeline here at exit")
    p.add_argument("--journal-out", default=None, metavar="PATH",
                   help="order-lifecycle flight recorder: append every "
                        "order's journey (submit/accept/reject/fills/"
                        "rest/cancel/payout with provenance stamps) "
                        "here; .bin/.kmej selects the compact binary "
                        "framing, anything else JSONL. Query with "
                        "kme-trace")
    p.add_argument("--trace-spans", action="store_true",
                   help="journal distributed-tracing span events "
                        "(ingress/plan/device/produce per order, keyed "
                        "by the deterministic group-local trace id) "
                        "alongside the lifecycle stream; needs "
                        "--journal-out. Stitch cluster-wide waterfalls "
                        "with kme-trace --cluster")
    p.add_argument("--journal-rotate-mb", type=int, default=None,
                   metavar="MB", help="rotate the journal (logrotate-"
                        "style PATH -> PATH.1 shifts) once the live "
                        "file exceeds MB MiB")
    p.add_argument("--journal-fsync", choices=("off", "batch"),
                   default="off",
                   help="batch = fsync the journal after every batch "
                        "(bounds loss to one batch); off = OS "
                        "buffering, flushed at checkpoints and exit")
    p.add_argument("--journal-keep", type=int, default=None, metavar="N",
                   help="retain at most N rotated journal segments — "
                        "but NEVER prune one newer than the oldest "
                        "retained snapshot (a standby restoring it "
                        "must still replay to the tip)")
    p.add_argument("--at-least-once", action="store_true",
                   help="disable the exactly-once output path (leader "
                        "epoch + fenced idempotent produce stamps) "
                        "that is otherwise on whenever "
                        "--checkpoint-dir is set: replayed post-"
                        "snapshot tails land on MatchOut again instead "
                        "of being suppressed broker-side")
    p.add_argument("--audit", action="store_true",
                   help="run the continuous invariant auditor in-"
                        "process: a shadow ledger replays the journal "
                        "stream per batch and checks conservation "
                        "invariants; violations increment "
                        "audit_violations, mark the heartbeat degraded "
                        "and dump a minimized repro (fixed mode only; "
                        "requires --journal-out)")
    p.add_argument("--audit-repro-dir", default=None, metavar="DIR",
                   help="write audit violation repro dumps here "
                        "(replayable with kme-trace --replay-repro)")
    p.add_argument("--slo-p99-ms", type=float, default=None,
                   metavar="MS",
                   help="latency SLO: keep the p99 of --slo-stage under "
                        "MS ms; sustained error-budget burn > 1 marks "
                        "the heartbeat degraded (the supervisor channel "
                        "audit violations already use) and flips the "
                        "slo_ok gauge")
    p.add_argument("--slo-stage", default="e2e",
                   choices=("ingress", "plan", "device", "produce",
                            "e2e", "consume"),
                   help="which latency stage the SLO judges")
    p.add_argument("--slo-budget", type=float, default=0.001,
                   metavar="FRAC",
                   help="allowed bad-event fraction (0.001 = 99.9%% of "
                        "orders must meet the target)")
    p.add_argument("--slo-min-ops", type=int, default=100, metavar="N",
                   help="observations per window before the SLO judges "
                        "(a quiet service is not a degraded one)")
    p.add_argument("--slo-min-records-per-sec", type=float, default=0.0,
                   metavar="R", help="optional throughput floor")
    p.add_argument("--pipeline", type=int, default=0, metavar="N",
                   help="double-buffered serving: keep up to N batches "
                        "in flight — batch N+1's parse/plan/dispatch "
                        "runs under batch N's device step; offsets and "
                        "checkpoints still advance only once a batch's "
                        "outputs are visible (needs engine=seq, "
                        "compat=fixed and the native host runtime; "
                        "anything else serves serial with a note)")
    p.add_argument("--group", default=None, metavar="K/N",
                   help="serve shard group K of an N-group multi-leader "
                        "topology (ISSUE 9): the service consumes "
                        "MatchIn.gK, produces MatchOut.gK, and lands "
                        "front-injected cross-shard transfer legs on "
                        "the stamped Xfer.gK evidence topic; pair with "
                        "a per-group --checkpoint-dir so the lease/"
                        "journal/snapshot roots are disjoint (kme-"
                        "supervise --groups N wires all of this)")
    p.add_argument("--tsdb", default=None, metavar="DIR",
                   help="append every heartbeat's metrics snapshot to "
                        "an on-disk time-series store in DIR (kme-prof "
                        "queries it); samples carry a monotonic "
                        "sample_seq persisted with the checkpoint so a "
                        "crash-resume dedups replayed heartbeats")
    p.add_argument("--profile", action="store_true",
                   help="always-on host sampling profiler: attributes "
                        "serve-loop wall time to pipeline stages "
                        "(parse/plan/dispatch/collect/produce) as "
                        "prof_stage_frac_* gauges")
    p.add_argument("--profile-artifact", default=None, metavar="PATH",
                   help="on close, write the per-backend transfer-vs-"
                        "compute JSON artifact (XLA cost_analysis + "
                        "measured H2D bandwidth) merged in place by "
                        "backend key")
    p.add_argument("--capture-dir", default=None, metavar="DIR",
                   help="trigger-based capture: on SLO burn or a p99 "
                        "exemplar past --capture-p99-us, record a "
                        "bounded profile window to DIR (span ids "
                        "resolve through kme-trace)")
    p.add_argument("--capture-p99-us", type=int, default=None,
                   metavar="US", help="exemplar e2e threshold that "
                        "fires a capture even without SLO burn")
    p.add_argument("--watch", action="append", default=None,
                   metavar="EXPR",
                   help="arm a live watchpoint evaluated at every "
                        "batch barrier (repeatable): balance[AID]<0, "
                        "position[AID,SYM]>X, depth[SYM]>=N, "
                        "spread[SYM]==0. Read-only — never gates "
                        "admission, never touches MatchOut; hits "
                        "write bounded captures to --capture-dir")
    p.add_argument("--annotate-rejects", action="store_true",
                   help="emit an ADDITIVE 'REJ'-keyed MatchOut record "
                        "naming each rejected order's rej_* reason "
                        "code (the IN/OUT stream stays byte-identical "
                        "to the reference)")
    args = p.parse_args(argv)

    import os

    from kme_tpu.bridge.broker import InProcessBroker
    from kme_tpu.bridge.provision import group_topics, provision
    from kme_tpu.bridge.service import MatchService
    from kme_tpu.bridge.tcp import parse_addr, serve_broker

    if args.watch:
        # fail fast on grammar errors instead of a mid-run warning
        from kme_tpu.telemetry.xray import XrayError, parse_watch

        try:
            for expr in args.watch:
                parse_watch(expr)
        except XrayError as e:
            print(f"kme-serve: {e}", file=sys.stderr)
            return 2

    group = None
    if args.group is not None:
        try:
            gk, gn = (int(x) for x in args.group.split("/", 1))
        except ValueError:
            print(f"kme-serve: --group wants K/N, got {args.group!r}",
                  file=sys.stderr)
            return 2
        if not (0 <= gk < gn):
            print(f"kme-serve: --group {gk}/{gn} out of range",
                  file=sys.stderr)
            return 2
        group = (gk, gn)

    if args.kafka is not None:
        from kme_tpu.bridge.kafka import KafkaBroker

        broker = KafkaBroker(args.kafka)
        srv = None
        print(f"kme-serve: using Kafka at {args.kafka}", file=sys.stderr)
    else:
        log_dir = args.log_dir
        if log_dir is None and args.checkpoint_dir is not None:
            log_dir = os.path.join(args.checkpoint_dir, "broker-log")
        overload = None
        if args.overload_high_lag is not None:
            from kme_tpu.bridge.broker import OverloadController

            overload = OverloadController(
                high_lag=args.overload_high_lag,
                low_lag=args.overload_low_lag,
                drain_lag=args.overload_drain_lag,
                p99_budget_ms=args.overload_p99_ms,
                account_cap=args.overload_account_cap)
        broker = InProcessBroker(persist_dir=log_dir,
                                 max_lag=args.max_lag,
                                 overload=overload)
        host, port = parse_addr(args.listen)
        srv, broker = serve_broker(host, port, broker)
        real_host, real_port = srv.server_address[:2]
        print(f"kme-serve: broker listening on {real_host}:{real_port}",
              file=sys.stderr)
    if args.auto_provision:
        provision(broker, topics=(group_topics(group[0])
                                  if group is not None and group[1] > 1
                                  else None))
    # exactly-once is the DEFAULT served contract once durability is on
    # (the reference shipped with it commented out, KProcessor.java:29);
    # --at-least-once opts back into the historical behavior. The Kafka
    # transport has no produce stamps and REJ annotations interleave at
    # non-deterministic batch boundaries — both fall back loudly.
    exactly_once = (args.checkpoint_dir is not None
                    and args.kafka is None
                    and not args.at_least_once)
    if exactly_once and args.annotate_rejects:
        print("kme-serve: --annotate-rejects interleaves REJ records at "
              "batch boundaries, which replay differently across a "
              "resume; falling back to at-least-once output",
              file=sys.stderr)
        exactly_once = False
    tracer = None
    if args.trace_out is not None:
        from kme_tpu.telemetry import TraceRecorder, install

        tracer = TraceRecorder()
        install(tracer)   # PhaseTimers pick it up process-wide
    svc = MatchService(broker, engine=args.engine, compat=args.compat,
                       batch=args.batch, symbols=args.symbols,
                       accounts=args.accounts, slots=args.slots,
                       max_fills=args.max_fills, width=args.width,
                       shards=args.shards, strict=args.strict,
                       checkpoint_dir=args.checkpoint_dir,
                       checkpoint_every=args.checkpoint_every,
                       checkpoint_keep=args.checkpoint_keep,
                       journal=args.journal_out,
                       journal_rotate_mb=args.journal_rotate_mb,
                       journal_fsync=args.journal_fsync,
                       journal_keep=args.journal_keep,
                       audit=args.audit,
                       audit_repro_dir=args.audit_repro_dir,
                       annotate_rejects=args.annotate_rejects,
                       exactly_once=exactly_once,
                       pipeline=args.pipeline,
                       group=group,
                       trace_spans=args.trace_spans,
                       tsdb=args.tsdb,
                       profile=args.profile,
                       profile_artifact=args.profile_artifact,
                       capture_dir=args.capture_dir,
                       capture_p99_us=args.capture_p99_us,
                       watch=args.watch,
                       slo=(None if args.slo_p99_ms is None else {
                           "stage": args.slo_stage,
                           "p99_ms": args.slo_p99_ms,
                           "budget": args.slo_budget,
                           "min_ops": args.slo_min_ops,
                           "min_records_per_s":
                               args.slo_min_records_per_sec}))
    msrv = None
    if args.metrics_port is not None:
        from kme_tpu.telemetry import start_metrics_server

        msrv = start_metrics_server(svc.telemetry, args.metrics_port)
        print(f"kme-serve: metrics on "
              f"http://{msrv.server_address[0]}:"
              f"{msrv.server_address[1]}/metrics", file=sys.stderr)
    rc = 0
    from kme_tpu.bridge.broker import BrokerFenced

    try:
        seen = svc.run(max_messages=args.max_messages,
                       idle_exit=args.idle_exit,
                       health_file=args.health_file,
                       health_every=args.health_every)
        if args.checkpoint_dir is not None:
            svc.checkpoint()
        print(f"kme-serve: processed {seen} records", file=sys.stderr)
        met = svc.metrics()
        if met is not None:
            import json

            print(f"kme-serve: metrics {json.dumps(met)}", file=sys.stderr)
    except BrokerFenced as e:
        # a newer leader epoch owns the stream (failover promotion or a
        # lease steal): nothing this incarnation could write will ever
        # be visible. Exit 75 (EX_TEMPFAIL) — the supervisor restarts
        # us and the fresh incarnation acquires the NEXT epoch.
        print(f"kme-serve: FENCED: {e}", file=sys.stderr)
        rc = 75
    except KeyboardInterrupt:
        pass
    finally:
        svc.close()     # flush + close the flight recorder
        if args.journal_out is not None and os.path.exists(
                args.journal_out):
            print(f"kme-serve: journal written to {args.journal_out}",
                  file=sys.stderr)
        if msrv is not None:
            msrv.shutdown()
        if tracer is not None:
            tracer.save(args.trace_out)
            print(f"kme-serve: trace written to {args.trace_out}",
                  file=sys.stderr)
        if srv is not None:
            srv.shutdown()
        if hasattr(broker, "close"):
            broker.close()
    return rc


if __name__ == "__main__":
    sys.exit(main())
