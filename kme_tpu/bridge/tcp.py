"""TCP process boundary for the broker: JSON-lines request/response.

The reference's clients cross a process boundary to the broker over the
Kafka wire protocol (kafkajs in Node, kafka-clients on the JVM). The
equivalent here is a deliberately small framed protocol — one JSON
object per line — carrying the three broker operations:

  {"op":"create_topic","topic":T,"partitions":1}  -> {"ok":true,"created":b}
  {"op":"topics"}                                 -> {"ok":true,"topics":{...}}
  {"op":"produce","topic":T,"key":K,"value":V}    -> {"ok":true,"offset":N}
  {"op":"fetch","topic":T,"offset":N,"max":M,
   "timeout_ms":W}                                -> {"ok":true,
                                                     "records":[[o,k,v],...]}
  {"op":"end_offset","topic":T}                   -> {"ok":true,"offset":N}
  {"op":"commit","topic":T,"offset":N}            -> {"ok":true}
  {"op":"sync"}                                   -> {"ok":true}
  {"op":"fence","epoch":E}                        -> {"ok":true}

Exactly-once produces additionally carry "epoch" and "out_seq" keys
(optional — absent means the unstamped at-least-once path); fetch rows
for stamped records come back as [o,k,v,epoch,out_seq], and rows whose
record carries a broker-admission timestamp append a sixth element:
[o,k,v,epoch,out_seq,ats] (microseconds, wall clock). Clients parse by
length, so old/new peers interoperate.

Errors come back as {"ok":false,"error":"..."}; the client raises
BrokerError (BrokerOverload when the reply carries
"code":"rej_overload" — the bounded-ingress shed; BrokerFenced for
"code":"fenced" — a stale-epoch produce, which callers must treat as
fatal, not retryable). `serve_broker` hosts an InProcessBroker for any
number of concurrent client connections (thread per connection — the
broker core is already thread-safe).
"""

from __future__ import annotations

import json
import socket
import socketserver
import threading
from typing import List, Optional

from kme_tpu import faults
from kme_tpu.bridge.broker import (BrokerError, BrokerFenced,
                                   BrokerOverload, InProcessBroker,
                                   Record)


def _row(r: Record) -> list:
    """Wire row for a fetched record — the shortest shape that loses
    nothing: [o,k,v], +[epoch,out_seq] when stamped, +[ats] when the
    broker recorded an admission time."""
    ats = getattr(r, "ats", None)
    if ats is not None:
        return [r.offset, r.key, r.value, r.epoch, r.out_seq, ats]
    if r.epoch is None and r.out_seq is None:
        return [r.offset, r.key, r.value]
    return [r.offset, r.key, r.value, r.epoch, r.out_seq]


class _Handler(socketserver.StreamRequestHandler):
    def handle(self) -> None:
        broker: InProcessBroker = self.server.broker  # type: ignore
        for raw in self.rfile:
            try:
                req = json.loads(raw)
                op = req.get("op")
                if op == "create_topic":
                    created = broker.create_topic(
                        req["topic"], int(req.get("partitions", 1)))
                    resp = {"ok": True, "created": created}
                elif op == "topics":
                    resp = {"ok": True, "topics": broker.topics()}
                elif op == "produce":
                    off = broker.produce(req["topic"], req.get("key"),
                                         req["value"],
                                         epoch=req.get("epoch"),
                                         out_seq=req.get("out_seq"))
                    resp = {"ok": True, "offset": off}
                elif op == "produce_batch":
                    # one round trip for a whole record batch — the bulk
                    # seeding path (kme-loadgen)
                    off = -1
                    for rec in req["records"]:
                        off = broker.produce(
                            req["topic"], rec[0], rec[1],
                            epoch=rec[2] if len(rec) > 2 else None,
                            out_seq=rec[3] if len(rec) > 3 else None)
                    resp = {"ok": True, "last_offset": off}
                elif op == "fetch":
                    recs = broker.fetch(
                        req["topic"], int(req["offset"]),
                        int(req.get("max", 1024)),
                        float(req.get("timeout_ms", 0)) / 1e3)
                    # rows: [o,k,v] bare, [o,k,v,epoch,out_seq] stamped,
                    # [o,k,v,epoch,out_seq,ats] with an admission stamp
                    resp = {"ok": True, "records": [_row(r) for r in recs]}
                elif op == "fence":
                    broker.fence(int(req["epoch"]))
                    resp = {"ok": True}
                elif op == "end_offset":
                    resp = {"ok": True,
                            "offset": broker.end_offset(req["topic"])}
                elif op == "commit":
                    broker.commit(req["topic"], int(req["offset"]))
                    resp = {"ok": True}
                elif op == "sync":
                    broker.sync()
                    resp = {"ok": True}
                else:
                    resp = {"ok": False, "error": f"unknown op {op!r}"}
            except (BrokerOverload, BrokerFenced) as e:
                resp = {"ok": False, "error": str(e), "code": e.code}
                # AIMD producer backoff hint from the adaptive overload
                # controller rides the rej_overload wire row
                if getattr(e, "backoff_ms", None) is not None:
                    resp["backoff_ms"] = e.backoff_ms
            except BrokerError as e:
                resp = {"ok": False, "error": str(e)}
            except (KeyError, ValueError, TypeError) as e:
                resp = {"ok": False, "error": f"bad request: {e}"}
            if faults.should("tcp.disconnect"):
                return      # drop the connection without replying
            blob = (json.dumps(resp, separators=(",", ":")) + "\n").encode()
            if faults.should("tcp.partial"):
                try:
                    self.wfile.write(blob[:max(1, len(blob) // 2)])
                    self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError):
                    pass
                return      # partial frame, then drop the connection
            try:
                self.wfile.write(blob)
            except (BrokenPipeError, ConnectionResetError):
                return


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def serve_broker(host: str = "127.0.0.1", port: int = 9092,
                 broker: Optional[InProcessBroker] = None):
    """Start serving `broker` on (host, port) in a daemon thread.
    Returns (server, broker); server.shutdown() stops it. port=0 picks a
    free port (server.server_address has the real one)."""
    broker = broker or InProcessBroker()
    srv = _Server((host, port), _Handler)
    srv.broker = broker  # type: ignore
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv, broker


class TcpBroker:
    """Client with the InProcessBroker API over the line protocol.

    The request/response framing is only sound while requests and
    replies stay in lockstep, so any socket timeout or partial read
    poisons the stream (a late reply would be read as the answer to the
    NEXT request). The client therefore invalidates the connection on
    any transport fault and transparently reconnects on the next call;
    blocking fetches extend the socket read deadline by their own
    server-side wait (`timeout_ms`) so a long poll is never misread as
    a transport fault."""

    def __init__(self, host: str, port: int, timeout: float = 30.0) -> None:
        self._addr = (host, port)
        self._timeout = timeout
        self._lock = threading.Lock()
        self._sock = None
        self._rfile = None
        self._connect()

    def _connect(self) -> None:
        self._sock = socket.create_connection(self._addr,
                                              timeout=self._timeout)
        self._rfile = self._sock.makefile("rb")

    def _invalidate(self) -> None:
        try:
            self.close()
        except OSError:
            pass
        self._sock = self._rfile = None

    def close(self) -> None:
        if self._sock is None:
            return
        try:
            self._rfile.close()
        finally:
            self._sock.close()

    def _call(self, req: dict, extra_wait: float = 0.0) -> dict:
        with self._lock:
            try:
                if self._sock is None:
                    self._connect()
                # read deadline covers the server's own blocking time
                self._sock.settimeout(self._timeout + extra_wait)
                self._sock.sendall(
                    (json.dumps(req, separators=(",", ":")) + "\n").encode())
                raw = self._rfile.readline()
            except (socket.timeout, OSError) as e:
                self._invalidate()
                raise BrokerError(
                    f"broker call failed ({e}); connection closed") from e
            if not raw:
                self._invalidate()
                raise BrokerError("broker connection closed")
            if not raw.endswith(b"\n"):
                self._invalidate()
                raise BrokerError("partial broker reply; connection closed")
        resp = json.loads(raw)
        if not resp.get("ok"):
            err = resp.get("error", "unknown broker error")
            if resp.get("code") == BrokerOverload.code:
                exc = BrokerOverload(err)
                if resp.get("backoff_ms") is not None:
                    exc.backoff_ms = int(resp["backoff_ms"])
                raise exc
            if resp.get("code") == BrokerFenced.code:
                raise BrokerFenced(err)
            raise BrokerError(err)
        return resp

    def create_topic(self, name: str, partitions: int = 1) -> bool:
        return self._call({"op": "create_topic", "topic": name,
                           "partitions": partitions})["created"]

    def topics(self) -> dict:
        return self._call({"op": "topics"})["topics"]

    def produce(self, topic: str, key: Optional[str], value: str,
                epoch: Optional[int] = None,
                out_seq: Optional[int] = None) -> int:
        req = {"op": "produce", "topic": topic, "key": key, "value": value}
        if epoch is not None:
            req["epoch"] = epoch
        if out_seq is not None:
            req["out_seq"] = out_seq
        return self._call(req)["offset"]

    def produce_batch(self, topic: str, records) -> int:
        """Append [(key, value), ...] in one round trip; returns the last
        offset (-1 for an empty batch)."""
        return self._call({"op": "produce_batch", "topic": topic,
                           "records": list(records)})["last_offset"]

    def fetch(self, topic: str, offset: int, max_records: int = 1024,
              timeout: float = 0.0) -> List[Record]:
        resp = self._call({"op": "fetch", "topic": topic, "offset": offset,
                           "max": max_records, "timeout_ms": timeout * 1e3},
                          extra_wait=timeout)
        return [Record(row[0], row[1], row[2],
                       row[3] if len(row) > 3 else None,
                       row[4] if len(row) > 4 else None,
                       row[5] if len(row) > 5 else None)
                for row in resp["records"]]

    def end_offset(self, topic: str) -> int:
        return self._call({"op": "end_offset", "topic": topic})["offset"]

    def commit(self, topic: str, offset: int) -> None:
        """Advance the consumer watermark that arms the broker's
        bounded-ingress `max_lag` check (see InProcessBroker.commit)."""
        self._call({"op": "commit", "topic": topic, "offset": offset})

    def sync(self) -> None:
        """fsync the broker's topic logs (see InProcessBroker.sync)."""
        self._call({"op": "sync"})

    def fence(self, epoch: int) -> None:
        """Fence every producer epoch below `epoch` (see
        InProcessBroker.fence)."""
        self._call({"op": "fence", "epoch": int(epoch)})


def parse_addr(addr: str) -> tuple:
    """'host:port' -> (host, port) (the broker address CLI flag)."""
    host, _, port = addr.rpartition(":")
    return host or "127.0.0.1", int(port)
