"""TCP process boundary for the broker: JSON-lines request/response.

The reference's clients cross a process boundary to the broker over the
Kafka wire protocol (kafkajs in Node, kafka-clients on the JVM). The
equivalent here is a deliberately small framed protocol — one JSON
object per line — carrying the three broker operations:

  {"op":"create_topic","topic":T,"partitions":1}  -> {"ok":true,"created":b}
  {"op":"topics"}                                 -> {"ok":true,"topics":{...}}
  {"op":"produce","topic":T,"key":K,"value":V}    -> {"ok":true,"offset":N}
  {"op":"fetch","topic":T,"offset":N,"max":M,
   "timeout_ms":W}                                -> {"ok":true,
                                                     "records":[[o,k,v],...]}
  {"op":"end_offset","topic":T}                   -> {"ok":true,"offset":N}
  {"op":"commit","topic":T,"offset":N}            -> {"ok":true}
  {"op":"sync"}                                   -> {"ok":true}
  {"op":"fence","epoch":E}                        -> {"ok":true}

Exactly-once produces additionally carry "epoch" and "out_seq" keys
(optional — absent means the unstamped at-least-once path); fetch rows
for stamped records come back as [o,k,v,epoch,out_seq], and rows whose
record carries a broker-admission timestamp append a sixth element:
[o,k,v,epoch,out_seq,ats] (microseconds, wall clock). Clients parse by
length, so old/new peers interoperate. Produce requests may carry an
"ats" admission stamp: the client stamps at its FIRST send attempt and
re-sends the same stamp when it retries the same record across a
reconnect, so ingress latency histograms include the reconnect delay
(coordinated-omission-safe) instead of restarting the clock.

Distributed tracing rides the same parse-by-length scheme: a produce
request may carry a "tid" trace word (transport-advisory — see
telemetry/dtrace.py; the durable log never stores it), and fetch rows
for records carrying one gain a seventh element
[o,k,v,epoch,out_seq,ats,tid] (ats padded with null when absent so the
position is stable).

**Binary framing (additive, auto-negotiated per message).** The server
peeks one byte per request: '{' (0x7B) opens the JSON line above;
0xB1 (wire.WIRE_MAGIC) opens a binary PRODUCE envelope — the 8-byte
frame header (magic, version, kind=FRAME_PRODUCE, flags, u32 body
length) followed by u16 topic-length + topic, u8 key-length (255 =
null) + key, three i64s (epoch, seq0, ats; INT64_MIN = absent), then
the 72-byte order frames themselves. The reply is the usual JSON line
({"ok":true,"n":N,"last_offset":O}); overload replies add "admitted"
(records kept before the shed) so binary producers resume from
buf[admitted*72:]. `fetch_bin` is the symmetric read path: a JSON
request, answered by a JSON header line ({"ok":true,"n":N,
"nbytes":B}) followed by B bytes of fixed-width rows — per record
i64 offset/epoch/out_seq/ats/tid (INT64_MIN = absent), u8 key-length
(255 = null) + key, u32 value-length + value. Both paths carry the
(epoch, out_seq) stamps and ats without a per-record dict on either
side; JSON stays fully supported on the same socket (COMPAT.md).

Errors come back as {"ok":false,"error":"..."}; the client raises
BrokerError (BrokerOverload when the reply carries
"code":"rej_overload" — the bounded-ingress shed; BrokerFenced for
"code":"fenced" — a stale-epoch produce, which callers must treat as
fatal, not retryable; malformed binary frames carry
"code":"rej_malformed" and raise ValueError). `serve_broker` hosts an
InProcessBroker for any number of concurrent client connections
(thread per connection — the broker core is already thread-safe).
"""

from __future__ import annotations

import json
import socket
import socketserver
import struct
import threading
from typing import List, Optional, Tuple

from kme_tpu import faults
from kme_tpu.bridge.broker import (BrokerError, BrokerFenced,
                                   BrokerOverload, InProcessBroker,
                                   Record)
from kme_tpu.wire import (FRAME_PRODUCE, WIRE_MAGIC, WIRE_VERSION,
                          WireFrameError, rej_name)

# binary envelope scaffolding (layout documented in the module
# docstring; the 8-byte header is wire.py's frame header)
_ENV_HDR = struct.Struct("<BBBBI")
_ENV_META = struct.Struct("<qqq")       # epoch, seq0, ats
_REC_HDR = struct.Struct("<qqqqq")      # offset, epoch, out_seq, ats, tid
_I64_NONE = -(1 << 63)                  # "absent" for optional i64s
_MAGIC_BYTE = bytes([WIRE_MAGIC])


def _opt(v: Optional[int]) -> int:
    return _I64_NONE if v is None else int(v)


def _unopt(v: int) -> Optional[int]:
    return None if v == _I64_NONE else v


def _row(r: Record) -> list:
    """Wire row for a fetched record — the shortest shape that loses
    nothing: [o,k,v], +[epoch,out_seq] when stamped, +[ats] when the
    broker recorded an admission time, +[tid] when the record carries a
    trace word (ats stays in position 5, null when absent)."""
    ats = getattr(r, "ats", None)
    tid = getattr(r, "tid", None)
    if tid is not None:
        return [r.offset, r.key, r.value, r.epoch, r.out_seq, ats, tid]
    if ats is not None:
        return [r.offset, r.key, r.value, r.epoch, r.out_seq, ats]
    if r.epoch is None and r.out_seq is None:
        return [r.offset, r.key, r.value]
    return [r.offset, r.key, r.value, r.epoch, r.out_seq]


class _Handler(socketserver.StreamRequestHandler):
    def _read_exact(self, n: int) -> bytes:
        data = self.rfile.read(n)
        if len(data) != n:        # client died mid-frame
            raise ConnectionResetError("short read inside binary frame")
        return data

    def _produce_frames_req(self, broker: InProcessBroker) -> dict:
        """Binary PRODUCE envelope: the magic byte was already consumed
        by the dispatch peek; read the rest of the 8-byte header, then
        the declared body, and hand the raw frames to the broker without
        building per-record dicts."""
        hdr = _MAGIC_BYTE + self._read_exact(_ENV_HDR.size - 1)
        _magic, version, kind, _flags, length = _ENV_HDR.unpack(hdr)
        body = self._read_exact(length) if length else b""
        # envelope validation mirrors wire.py's frame-validation order
        if version != WIRE_VERSION:
            raise WireFrameError("version_skew",
                                 f"envelope version {version}, "
                                 f"expected {WIRE_VERSION}")
        if kind != FRAME_PRODUCE:
            raise WireFrameError("bad_kind", f"envelope kind {kind}")
        off = 2
        if len(body) < off:
            raise WireFrameError("truncated", "envelope shorter than "
                                 "its topic-length field")
        (tlen,) = struct.unpack_from("<H", body, 0)
        if len(body) < off + tlen + 1:
            raise WireFrameError("truncated", "envelope topic/key header")
        topic = body[off:off + tlen].decode("utf-8", "replace")
        off += tlen
        klen = body[off]
        off += 1
        key: Optional[str] = None
        if klen != 255:
            if len(body) < off + klen:
                raise WireFrameError("truncated", "envelope key")
            key = body[off:off + klen].decode("utf-8", "replace")
            off += klen
        if len(body) < off + _ENV_META.size:
            raise WireFrameError("truncated", "envelope epoch/seq/ats")
        epoch, seq0, ats = _ENV_META.unpack_from(body, off)
        off += _ENV_META.size
        n, last = broker.produce_frames(
            topic, key, body[off:], epoch=_unopt(epoch),
            seq0=_unopt(seq0), ats=_unopt(ats))
        return {"ok": True, "n": n, "last_offset": last}

    def handle(self) -> None:
        broker: InProcessBroker = self.server.broker  # type: ignore
        while True:
            try:
                first = self.rfile.read(1)
            except (ConnectionResetError, OSError):
                return
            if not first:
                return
            tail = b""      # binary payload appended after the JSON line
            try:
                if first == _MAGIC_BYTE:
                    resp = self._produce_frames_req(broker)
                else:
                    raw = first + self.rfile.readline()
                    resp, tail = self._dispatch(broker, raw)
            except ConnectionResetError:
                return
            except WireFrameError as e:
                # malformed binary input is a clean protocol error, not
                # a dropped connection — the stream stays in lockstep
                # because the envelope header told us how much to read
                resp = {"ok": False, "error": str(e),
                        "code": rej_name(e.code)}
            except (BrokerOverload, BrokerFenced) as e:
                resp = {"ok": False, "error": str(e), "code": e.code}
                # AIMD producer backoff hint from the adaptive overload
                # controller rides the rej_overload wire row
                if getattr(e, "backoff_ms", None) is not None:
                    resp["backoff_ms"] = e.backoff_ms
                # binary producers resume from buf[admitted*FRAME_SIZE:]
                if getattr(e, "admitted", None) is not None:
                    resp["admitted"] = e.admitted
            except BrokerError as e:
                resp = {"ok": False, "error": str(e)}
            except (KeyError, ValueError, TypeError) as e:
                resp = {"ok": False, "error": f"bad request: {e}"}
            if faults.should("tcp.disconnect"):
                return      # drop the connection without replying
            blob = (json.dumps(resp, separators=(",", ":")) + "\n").encode()
            blob += tail
            if faults.should("tcp.partial"):
                try:
                    self.wfile.write(blob[:max(1, len(blob) // 2)])
                    self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError):
                    pass
                return      # partial frame, then drop the connection
            try:
                self.wfile.write(blob)
            except (BrokenPipeError, ConnectionResetError):
                return

    def _dispatch(self, broker: InProcessBroker,
                  raw: bytes) -> Tuple[dict, bytes]:
        """One JSON request -> (reply dict, binary tail). Broker/protocol
        exceptions propagate to handle()'s shared error mapping."""
        tail = b""
        req = json.loads(raw)
        op = req.get("op")
        if op == "create_topic":
            created = broker.create_topic(
                req["topic"], int(req.get("partitions", 1)))
            resp = {"ok": True, "created": created}
        elif op == "topics":
            resp = {"ok": True, "topics": broker.topics()}
        elif op == "produce":
            off = broker.produce(req["topic"], req.get("key"),
                                 req["value"],
                                 epoch=req.get("epoch"),
                                 out_seq=req.get("out_seq"),
                                 ats=req.get("ats"),
                                 tid=req.get("tid"))
            resp = {"ok": True, "offset": off}
        elif op == "produce_batch":
            # one round trip for a whole record batch — the bulk
            # seeding path (kme-loadgen)
            off = -1
            for rec in req["records"]:
                off = broker.produce(
                    req["topic"], rec[0], rec[1],
                    epoch=rec[2] if len(rec) > 2 else None,
                    out_seq=rec[3] if len(rec) > 3 else None)
            resp = {"ok": True, "last_offset": off}
        elif op == "fetch":
            recs = broker.fetch(
                req["topic"], int(req["offset"]),
                int(req.get("max", 1024)),
                float(req.get("timeout_ms", 0)) / 1e3)
            # rows: [o,k,v] bare, [o,k,v,epoch,out_seq] stamped,
            # [o,k,v,epoch,out_seq,ats] with an admission stamp
            resp = {"ok": True, "records": [_row(r) for r in recs]}
        elif op == "fetch_bin":
            recs = broker.fetch(
                req["topic"], int(req["offset"]),
                int(req.get("max", 1024)),
                float(req.get("timeout_ms", 0)) / 1e3)
            parts = []
            for r in recs:
                kb = b"" if r.key is None else r.key.encode()
                vb = r.value.encode()
                parts.append(
                    _REC_HDR.pack(r.offset, _opt(r.epoch),
                                  _opt(r.out_seq),
                                  _opt(getattr(r, "ats", None)),
                                  _opt(getattr(r, "tid", None)))
                    + bytes([255 if r.key is None else len(kb)]) + kb
                    + struct.pack("<I", len(vb)) + vb)
            tail = b"".join(parts)
            resp = {"ok": True, "n": len(recs), "nbytes": len(tail)}
        elif op == "fence":
            broker.fence(int(req["epoch"]))
            resp = {"ok": True}
        elif op == "end_offset":
            resp = {"ok": True,
                    "offset": broker.end_offset(req["topic"])}
        elif op == "commit":
            broker.commit(req["topic"], int(req["offset"]))
            resp = {"ok": True}
        elif op == "sync":
            broker.sync()
            resp = {"ok": True}
        else:
            resp = {"ok": False, "error": f"unknown op {op!r}"}
        return resp, tail


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def serve_broker(host: str = "127.0.0.1", port: int = 9092,
                 broker: Optional[InProcessBroker] = None):
    """Start serving `broker` on (host, port) in a daemon thread.
    Returns (server, broker); server.shutdown() stops it. port=0 picks a
    free port (server.server_address has the real one)."""
    broker = broker or InProcessBroker()
    srv = _Server((host, port), _Handler)
    srv.broker = broker  # type: ignore
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv, broker


class TcpBroker:
    """Client with the InProcessBroker API over the line protocol.

    The request/response framing is only sound while requests and
    replies stay in lockstep, so any socket timeout or partial read
    poisons the stream (a late reply would be read as the answer to the
    NEXT request). The client therefore invalidates the connection on
    any transport fault and transparently reconnects on the next call;
    blocking fetches extend the socket read deadline by their own
    server-side wait (`timeout_ms`) so a long poll is never misread as
    a transport fault."""

    def __init__(self, host: str, port: int, timeout: float = 30.0,
                 clock=None) -> None:
        from kme_tpu.bridge.clock import WALL

        # the clock seam (bridge/clock.py): admission re-stamping of
        # retried produces reads this object, never the wall directly
        self._clock = clock or WALL
        self._addr = (host, port)
        self._timeout = timeout
        self._lock = threading.Lock()
        self._sock = None
        self._rfile = None
        # (fingerprint, ats) of the last produce that died on a transport
        # fault: a retry of the SAME record reuses its original admission
        # stamp, so the reconnect delay lands inside the latency
        # histogram instead of restarting the clock (coordinated
        # omission). Cleared on success, overload, and fence — those are
        # broker verdicts, not transport faults.
        self._pending: Optional[Tuple[tuple, int]] = None
        self._connect()

    def _connect(self) -> None:
        self._sock = socket.create_connection(self._addr,
                                              timeout=self._timeout)
        self._rfile = self._sock.makefile("rb")

    def _invalidate(self) -> None:
        try:
            self.close()
        except OSError:
            pass
        self._sock = self._rfile = None

    def close(self) -> None:
        if self._sock is None:
            return
        try:
            self._rfile.close()
        finally:
            self._sock.close()

    def _roundtrip(self, payload: bytes,
                   extra_wait: float = 0.0) -> Tuple[dict, bytes]:
        """Send one request frame (JSON line or binary envelope), read
        the JSON reply line plus any binary tail the reply announces via
        "nbytes". Returns (reply, tail)."""
        with self._lock:
            try:
                if self._sock is None:
                    self._connect()
                # read deadline covers the server's own blocking time
                self._sock.settimeout(self._timeout + extra_wait)
                self._sock.sendall(payload)
                raw = self._rfile.readline()
            except (socket.timeout, OSError) as e:
                self._invalidate()
                raise BrokerError(
                    f"broker call failed ({e}); connection closed") from e
            if not raw:
                self._invalidate()
                raise BrokerError("broker connection closed")
            if not raw.endswith(b"\n"):
                self._invalidate()
                raise BrokerError("partial broker reply; connection closed")
            resp = json.loads(raw)
            body = b""
            nbytes = resp.get("nbytes")
            if resp.get("ok") and nbytes:
                try:
                    body = self._rfile.read(int(nbytes))
                except (socket.timeout, OSError) as e:
                    self._invalidate()
                    raise BrokerError(
                        f"broker call failed ({e}); connection closed") from e
                if len(body) != int(nbytes):
                    self._invalidate()
                    raise BrokerError(
                        "partial broker reply; connection closed")
        if not resp.get("ok"):
            err = resp.get("error", "unknown broker error")
            if resp.get("code") == BrokerOverload.code:
                exc = BrokerOverload(err)
                if resp.get("backoff_ms") is not None:
                    exc.backoff_ms = int(resp["backoff_ms"])
                if resp.get("admitted") is not None:
                    exc.admitted = int(resp["admitted"])
                raise exc
            if resp.get("code") == BrokerFenced.code:
                raise BrokerFenced(err)
            if resp.get("code") == "rej_malformed":
                raise ValueError(err)
            raise BrokerError(err)
        return resp, body

    def _call(self, req: dict, extra_wait: float = 0.0) -> dict:
        payload = (json.dumps(req, separators=(",", ":")) + "\n").encode()
        return self._roundtrip(payload, extra_wait)[0]

    def _ats_for(self, fp: tuple) -> int:
        """Admission stamp for a produce attempt: reuse the stamp of a
        transport-faulted attempt at the SAME record, else stamp now."""
        pend = self._pending
        if pend is not None and pend[0] == fp:
            return pend[1]
        return self._clock.time_us()

    def create_topic(self, name: str, partitions: int = 1) -> bool:
        return self._call({"op": "create_topic", "topic": name,
                           "partitions": partitions})["created"]

    def topics(self) -> dict:
        return self._call({"op": "topics"})["topics"]

    def produce(self, topic: str, key: Optional[str], value: str,
                epoch: Optional[int] = None,
                out_seq: Optional[int] = None,
                tid: Optional[int] = None) -> int:
        fp = ("produce", topic, key, value, epoch, out_seq)
        ats = self._ats_for(fp)
        req = {"op": "produce", "topic": topic, "key": key, "value": value,
               "ats": ats}
        if epoch is not None:
            req["epoch"] = epoch
        if out_seq is not None:
            req["out_seq"] = out_seq
        if tid is not None:
            req["tid"] = tid
        try:
            off = self._call(req)["offset"]
        except (BrokerOverload, BrokerFenced):
            self._pending = None    # broker verdict, stamp expires
            raise
        except BrokerError:
            self._pending = (fp, ats)   # transport fault: keep the stamp
            raise
        self._pending = None
        return off

    def produce_frames(self, topic: str, key: Optional[str], buf: bytes,
                       epoch: Optional[int] = None,
                       seq0: Optional[int] = None) -> Tuple[int, int]:
        """Append a buffer of 72-byte binary order frames in one round
        trip — no per-record dicts on either side. Returns (n appended,
        last offset). On BrokerOverload the exception's `.admitted`
        counts the prefix kept; resume from buf[admitted*FRAME_SIZE:]."""
        fp = ("frames", topic, key, buf, epoch, seq0)
        ats = self._ats_for(fp)
        tb = topic.encode()
        kb = b"" if key is None else key.encode()
        body = (struct.pack("<H", len(tb)) + tb
                + bytes([255 if key is None else len(kb)]) + kb
                + _ENV_META.pack(_opt(epoch), _opt(seq0), ats) + buf)
        payload = _ENV_HDR.pack(WIRE_MAGIC, WIRE_VERSION, FRAME_PRODUCE,
                                0, len(body)) + body
        try:
            resp, _ = self._roundtrip(payload)
        except (BrokerOverload, BrokerFenced):
            self._pending = None    # broker verdict, stamp expires
            raise
        except BrokerError:
            self._pending = (fp, ats)   # transport fault: keep the stamp
            raise
        self._pending = None
        return resp["n"], resp["last_offset"]

    def produce_batch(self, topic: str, records) -> int:
        """Append [(key, value), ...] in one round trip; returns the last
        offset (-1 for an empty batch)."""
        return self._call({"op": "produce_batch", "topic": topic,
                           "records": list(records)})["last_offset"]

    def fetch(self, topic: str, offset: int, max_records: int = 1024,
              timeout: float = 0.0) -> List[Record]:
        resp = self._call({"op": "fetch", "topic": topic, "offset": offset,
                           "max": max_records, "timeout_ms": timeout * 1e3},
                          extra_wait=timeout)
        return [Record(row[0], row[1], row[2],
                       row[3] if len(row) > 3 else None,
                       row[4] if len(row) > 4 else None,
                       row[5] if len(row) > 5 else None,
                       row[6] if len(row) > 6 else None)
                for row in resp["records"]]

    def fetch_bin(self, topic: str, offset: int, max_records: int = 1024,
                  timeout: float = 0.0) -> List[Record]:
        """fetch() over the binary reply tail: one JSON header line, then
        fixed-width rows — stamps and ats decode straight from bytes."""
        resp, body = self._roundtrip(
            (json.dumps({"op": "fetch_bin", "topic": topic,
                         "offset": offset, "max": max_records,
                         "timeout_ms": timeout * 1e3},
                        separators=(",", ":")) + "\n").encode(),
            extra_wait=timeout)
        recs: List[Record] = []
        off = 0
        for _ in range(int(resp["n"])):
            o, epoch, out_seq, ats, tid = _REC_HDR.unpack_from(body, off)
            off += _REC_HDR.size
            klen = body[off]
            off += 1
            key = None
            if klen != 255:
                key = body[off:off + klen].decode()
                off += klen
            (vlen,) = struct.unpack_from("<I", body, off)
            off += 4
            value = body[off:off + vlen].decode()
            off += vlen
            recs.append(Record(o, key, value, _unopt(epoch),
                               _unopt(out_seq), _unopt(ats),
                               _unopt(tid)))
        return recs

    def end_offset(self, topic: str) -> int:
        return self._call({"op": "end_offset", "topic": topic})["offset"]

    def commit(self, topic: str, offset: int) -> None:
        """Advance the consumer watermark that arms the broker's
        bounded-ingress `max_lag` check (see InProcessBroker.commit)."""
        self._call({"op": "commit", "topic": topic, "offset": offset})

    def sync(self) -> None:
        """fsync the broker's topic logs (see InProcessBroker.sync)."""
        self._call({"op": "sync"})

    def fence(self, epoch: int) -> None:
        """Fence every producer epoch below `epoch` (see
        InProcessBroker.fence)."""
        self._call({"op": "fence", "epoch": int(epoch)})


def parse_addr(addr: str) -> tuple:
    """'host:port' -> (host, port) (the broker address CLI flag)."""
    host, _, port = addr.rpartition(":")
    return host or "127.0.0.1", int(port)
