"""MatchService: the engine service behind the MatchIn/MatchOut topics.

The reference role: Kafka Streams pulls records from `MatchIn`, the
processor forwards the pre-image with key "IN", processes, and forwards
the result/fill stream with key "OUT" to `MatchOut`
(/root/reference/src/main/java/KProcessor.java:96-126). Here the same
contract is a poll loop over the broker API with a pluggable engine:

- engine="lanes"  — the device throughput engine (fixed-mode semantics,
  micro-batched through LaneSession.process_wire). The batch boundary
  replaces the reference's per-record commit (KProcessor.java:125,
  SURVEY.md §7 H5): offsets advance only after a batch's outputs are
  produced.
- engine="oracle" — the scalar reference replica (compat java|fixed),
  quirk-exact per message; the slow-but-byte-faithful configuration.
- engine="native" — the C++ port of the same quirk-exact semantics
  (kme_tpu/native/oracle.py): the FAST java-compat path (the parallel
  engine cannot be quirk-exact under Q11 — COMPAT.md).

Malformed values (JSON Jackson would reject) kill the reference's
stream thread (KProcessor.java:513-517); the service instead drops the
record with a stderr note — a deliberate fix, flagged by `strict=True`
which replicates the reference behavior by raising.

Output contract: by default AT-LEAST-ONCE (the reference, with Kafka's
exactly-once commented out at KProcessor.java:29 — crash + resume
replays the post-snapshot tail). `exactly_once=True` upgrades that to
exactly-once VISIBLE output: the service acquires a leader epoch
(bridge/lease.py), stamps every MatchOut produce with
`(epoch, out_seq)` (wire.ProduceStamp), and the broker fences stale
epochs and suppresses replayed stamps (bridge/broker.py), so the
durable MatchOut log itself carries each record exactly once.
`follower=True` runs the service as a hot-standby replica: produces are
discarded (but out_seq still counts them, so a promotion can continue
the stamp stream), checkpoints are skipped, and no lease is held until
promotion (bridge/replica.py).
"""

from __future__ import annotations

import sys
from typing import Optional

from kme_tpu import faults

TOPIC_IN = "MatchIn"    # topic.js:17
TOPIC_OUT = "MatchOut"  # topic.js:21


class MatchService:
    def __init__(self, broker, engine: str = "lanes",
                 compat: str = "fixed", batch: int = 1024,
                 symbols: int = 1024, accounts: int = 4096,
                 slots: int = 128, max_fills: int = 16,
                 width: int = 8, shards: int = 1,
                 strict: bool = False,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint_every: int = 4096,
                 checkpoint_keep: Optional[int] = None,
                 journal=None, journal_rotate_mb: Optional[int] = None,
                 journal_fsync: str = "off",
                 journal_keep: Optional[int] = None,
                 audit: bool = False,
                 audit_repro_dir: Optional[str] = None,
                 annotate_rejects: bool = False,
                 exactly_once: bool = False,
                 follower: bool = False,
                 pipeline: int = 0,
                 group=None,
                 slo=None,
                 trace_spans: bool = False,
                 tsdb: Optional[str] = None,
                 profile: bool = False,
                 profile_artifact: Optional[str] = None,
                 capture_dir: Optional[str] = None,
                 capture_p99_us: Optional[int] = None,
                 watch=None, clock=None) -> None:
        if engine not in ("lanes", "seq", "oracle", "native"):
            raise ValueError(f"unknown engine {engine!r}")
        if compat not in ("java", "fixed"):
            raise ValueError(f"unknown compat {compat!r}")
        if engine == "lanes" and compat != "fixed":
            raise ValueError("the lanes engine is fixed-mode only; use "
                             "engine='seq' (stock wire surface), "
                             "'native' or 'oracle' for compat='java'")
        # java-mode seq sessions checkpoint via the seqjava canonical
        # form (runtime/javasnap.py) since round 5 — no engine/compat
        # combination is excluded from durability
        self.broker = broker
        # the clock seam (bridge/clock.py): every sleep/backoff and
        # interval read below goes through this object so the simulator
        # can own time; production passes None and pays one attribute
        # hop to the shared WallClock
        from kme_tpu.bridge.clock import WALL

        self.clock = clock or WALL
        # multi-leader shard group (ISSUE 9): group=(k, n) namespaces
        # every durable artifact this service touches on the broker —
        # its input/output topics become "MatchIn.g{k}"/"MatchOut.g{k}"
        # and front-injected cross-shard transfer legs are diverted to
        # a stamped per-group "Xfer.g{k}" topic (the durable dedup
        # evidence) instead of the merged MatchOut feed. Lease, journal
        # and checkpoint namespacing happens one level up: kme-serve
        # gives each group its own --checkpoint-dir root.
        if group is not None:
            gk, gn = int(group[0]), int(group[1])
            if gn < 1 or not (0 <= gk < gn):
                raise ValueError(f"group {gk}/{gn} out of range")
        else:
            gk, gn = 0, 1
        self.group_id, self.group_count = gk, gn
        grouped = group is not None and gn > 1
        self.topic_in = f"{TOPIC_IN}.g{gk}" if grouped else TOPIC_IN
        self.topic_out = f"{TOPIC_OUT}.g{gk}" if grouped else TOPIC_OUT
        self.topic_xfer = f"Xfer.g{gk}" if grouped else None
        # cross-shard balance-transfer ledger (checkpointed in the
        # snapshot's extra meta so a resume reports continuous totals):
        # legs = applied transfer legs, credits/debits = amounts moved
        # in/out of this group's accounts, rejected = legs the engine
        # refused (shadow-ledger shortfall at the front door),
        # broadcasts = CREATE_BALANCE copies suppressed here
        self._xfer = {"legs": 0, "credits": 0, "debits": 0,
                      "rejected": 0, "broadcasts": 0}
        self._xfer_mark = None
        if grouped:
            from kme_tpu.bridge.front import _MARK_SUB

            self._xfer_mark = _MARK_SUB
            create = getattr(broker, "create_topic", None)
            if create is not None:
                from kme_tpu.bridge.broker import BrokerError

                try:
                    create(self.topic_xfer)
                except BrokerError:
                    pass    # already provisioned
        self.engine_kind = engine
        self._compat = compat
        self.batch = batch
        self.strict = strict
        self.offset = 0
        self._session = self._oracle = self._native = None
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_every = checkpoint_every
        self.checkpoint_keep = checkpoint_keep
        self._last_ckpt_offset = 0
        self._req_symbols, self._req_accounts = symbols, accounts
        self._req_slots, self._req_max_fills = slots, max_fills
        self._last_engine_pub = 0.0
        self._journal_arg = journal
        self._journal_rotate_mb = journal_rotate_mb
        self._journal_fsync = journal_fsync
        self._journal_keep = journal_keep
        self._audit_arg = audit
        self._audit_repro_dir = audit_repro_dir
        self.annotate_rejects = annotate_rejects
        self.exactly_once = exactly_once
        self.follower = follower
        # double-buffered serving (SURVEY.md §7 H5): up to `pipeline`
        # batches stay in flight — batch N+1's parse/plan/dispatch runs
        # under batch N's device step; offsets/checkpoints advance only
        # at collect time, so the durability contract is unchanged.
        # Needs the seq engine (submit/collect), fixed mode and the
        # native host runtime (buffer reconstruction); anything else
        # serves serial with a note.
        self.pipeline = 0
        self._pipe = None
        if pipeline:
            from kme_tpu.native import load_library

            if (engine == "seq" and compat == "fixed"
                    and not annotate_rejects
                    and load_library() is not None):
                import collections

                self.pipeline = int(pipeline)
                self._pipe = collections.deque()
            else:
                print("kme-serve: --pipeline needs engine=seq, "
                      "compat=fixed, the native host runtime and no "
                      "--annotate-rejects; serving serial",
                      file=sys.stderr)
        self.epoch: Optional[int] = None  # leader fencing token
        self.out_seq = 0                  # next MatchOut produce stamp
        if exactly_once and checkpoint_dir is None:
            raise ValueError("exactly_once needs checkpoint_dir (the "
                             "leader-epoch lease lives there)")
        if exactly_once and annotate_rejects:
            # REJ annotations interleave at BATCH boundaries, and batch
            # boundaries are not deterministic across a resume — the
            # out_seq stamp stream would diverge from the original and
            # the broker would dedup the wrong records
            raise ValueError("exactly_once is incompatible with "
                             "annotate_rejects (REJ records interleave "
                             "at non-deterministic batch boundaries)")
        self.degraded = None        # set by the invariant auditor
        # distributed tracing (telemetry/dtrace.py): journal per-order
        # "span" events keyed by local_tid(group, broker offset) — the
        # stitcher joins them to the front's global trace ids offline
        self.trace_spans = bool(trace_spans)
        # continuous profiling & history (ISSUE 16): metrics history on
        # disk at heartbeat cadence, the sampling host profiler, the
        # per-backend transfer/compute artifact, trigger captures
        self._tsdb_arg = tsdb
        self._profile_arg = bool(profile)
        self._profile_artifact = profile_artifact
        self._capture_dir = capture_dir
        self._capture_p99_us = capture_p99_us
        self.tsdb = None
        self.profiler = None
        self.capture = None
        # live watchpoints (ISSUE 17): deterministic predicates over the
        # shadow ledger, evaluated at every batch barrier. Read-only:
        # they never gate admission and never touch MatchOut bytes
        self._watch_arg = list(watch or [])
        self.watch = None
        # monotonic heartbeat-sample sequence: persisted across restart
        # via the checkpoint's additive `extra` meta so TSDB ingestion
        # dedups replayed samples exactly like the broker dedups
        # (epoch, out_seq) produce stamps
        self.sample_seq = 0
        self._slo_arg = slo         # dict of SLO kwargs, or None
        self.slo = None
        self._slo_reason = None
        # adaptive-shed annotations: controller sheds happen on the TCP
        # produce thread; queue the details and emit REJ rows (with
        # backlog/threshold/state) from the poll thread so shed storms
        # are debuggable from the output stream alone
        self._shed_pending = None
        if (annotate_rejects
                and getattr(broker, "overload", None) is not None
                and hasattr(broker, "shed_observer")):
            import collections

            q = collections.deque(maxlen=65536)
            self._shed_pending = q
            broker.shed_observer = lambda _topic, d: q.append(d)
        # control-plane flight recorder (telemetry/events.py): the serve
        # process's own durable event stream — lease grants, overload
        # state transitions — living next to the checkpoints so
        # kme-events merges it with the supervisor/standby logs. The
        # heartbeat exports its committed-bytes cursor
        # (events_last_offset/events_lag_bytes) so kme-agg can flag a
        # frozen recorder under an otherwise-live process
        self.events = None
        if checkpoint_dir is not None:
            from kme_tpu.telemetry import events as cpevents

            src = "follower" if self.follower else "serve"
            if self.group_count > 1:
                src = f"{src}.g{self.group_id}"
            try:
                self.events = cpevents.open_log(
                    checkpoint_dir, src, clock=self.clock.time)
            except OSError:
                self.events = None
            ctl = getattr(broker, "overload", None)
            if self.events is not None and ctl is not None:
                ev = self.events
                gid = self.group_id if self.group_count > 1 else None
                names = type(ctl).STATE_NAMES

                def _overload_event(prev, new):
                    ev.emit("overload.transition",
                            severity="warn" if new else "info",
                            group=gid, from_state=names[prev],
                            to_state=names[new],
                            backoff_ms=ctl.backoff_ms)

                ctl.on_transition = _overload_event
        resumed = False
        if checkpoint_dir is not None:
            resumed = self._try_resume(engine, compat, shards, width)
        if resumed:
            self._restore_sample_seq()
            self._init_exactly_once(resumed=True)
            self._init_telemetry()
            self._init_observability(resumed=True)
            self._commit_watermark()
            return
        if engine == "lanes":
            from kme_tpu.engine.lanes import LaneConfig
            from kme_tpu.runtime.session import LaneSession

            cfg = LaneConfig(lanes=symbols, slots=slots, accounts=accounts,
                             max_fills=max_fills)
            self._session = LaneSession(cfg, shards=shards, width=width)
        elif engine == "seq":
            self._session = self._make_seq_session()
        elif engine == "native":
            from kme_tpu.native.oracle import NativeOracleEngine

            kw = ({"book_slots": slots, "max_fills": max_fills}
                  if compat == "fixed" else {})
            self._native = NativeOracleEngine(compat, **kw)
        elif engine == "oracle":
            from kme_tpu.oracle import OracleEngine

            # the capacity envelope is a fixed-mode concept; java compat
            # replicates the reference's unbounded stores
            kw = ({"book_slots": slots, "max_fills": max_fills}
                  if compat == "fixed" else {})
            self._oracle = OracleEngine(compat, **kw)
        else:
            raise ValueError(f"unknown engine {engine!r}")
        self._init_exactly_once(resumed=False)
        self._init_telemetry()
        self._init_observability(resumed=False)
        self._commit_watermark()

    def _restore_sample_seq(self) -> None:
        """Heartbeat sample_seq continuation across a resume — read
        from the snapshot's additive extra meta REGARDLESS of the
        exactly-once setting (metrics history is not an exactly-once
        feature; any checkpointed service keeps a continuous TSDB
        sequence)."""
        from kme_tpu.runtime import checkpoint as ck

        extra = ck.snapshot_extra(self.checkpoint_dir, self.offset)
        try:
            self.sample_seq = max(0, int(extra.get("sample_seq", 0)))
        except (TypeError, ValueError):
            self.sample_seq = 0

    def _init_exactly_once(self, resumed: bool) -> None:
        """Exactly-once startup: restore the produce-stamp cursor from
        the snapshot's extra meta, then (leaders only) acquire the next
        leader epoch and fence every predecessor at the broker. The
        explicit fence matters: a promoted/restarted broker reload only
        learns PRIOR epochs from the log stamps, so without it a zombie
        old leader holding the previous epoch would still get through.
        A follower restores the cursor but holds no lease — its
        produces are discarded until promotion
        (bridge/replica.py)."""
        if not self.exactly_once:
            return
        if resumed:
            from kme_tpu.runtime import checkpoint as ck

            extra = ck.snapshot_extra(self.checkpoint_dir, self.offset)
            try:
                self.out_seq = int(extra.get("out_seq", 0))
            except (TypeError, ValueError):
                self.out_seq = 0
            pending = extra.get("pending_reserve")
            if isinstance(pending, dict):
                # cross-shard transfer ledger survives the restart so
                # replayed legs regenerate the same totals (the broker
                # watermark suppresses their duplicate stamps)
                for k in self._xfer:
                    try:
                        self._xfer[k] = int(pending.get(k, 0))
                    except (TypeError, ValueError):
                        pass
        if self.follower:
            return
        import inspect

        from kme_tpu.bridge import lease

        try:
            params = inspect.signature(self.broker.produce).parameters
        except (TypeError, ValueError):
            params = {}
        if "out_seq" not in params:
            # e.g. the Kafka transport: no produce stamps, no fencing —
            # fall back loudly to the at-least-once contract
            print("kme-serve: broker transport has no produce stamps; "
                  "exactly-once disabled (at-least-once output)",
                  file=sys.stderr)
            self.exactly_once = False
            return
        self.epoch = lease.acquire(self.checkpoint_dir,
                                   events=self.events)
        fence = getattr(self.broker, "fence", None)
        if fence is not None:
            fence(self.epoch)
        print(f"kme-serve: leader epoch {self.epoch} (out_seq resumes "
              f"at {self.out_seq})", file=sys.stderr)

    def _commit_watermark(self) -> None:
        """Advance the broker's consumer watermark for MatchIn — this
        arms (and continuously re-arms) the bounded-ingress max_lag
        check: producers past the bound get a wire-level rej_overload
        (BrokerOverload) instead of growing the backlog unboundedly."""
        commit = getattr(self.broker, "commit", None)
        if commit is None:
            return
        from kme_tpu.bridge.broker import BrokerError

        try:
            commit(self.topic_in, self.offset)
        except BrokerError:
            pass        # topic not provisioned yet / transport blip

    def _init_observability(self, resumed: bool) -> None:
        """Flight recorder + invariant auditor wiring. The journal
        subscribes the auditor as an observer, so the shadow replay
        sees exactly what lands in the journal file; on resume the
        journal is rewound to the snapshot offset (the at-least-once
        tail replay would otherwise journal twice) and the auditor is
        seeded from the restored engine state."""
        import os

        from kme_tpu.telemetry import InvariantAuditor, Journal

        self.journal = None
        self.auditor = None
        j = self._journal_arg
        if isinstance(j, str):
            rb = (self._journal_rotate_mb * (1 << 20)
                  if self._journal_rotate_mb else None)
            guard = None
            if self.checkpoint_dir is not None:
                # retention coupling: rotated journal segments may only
                # be pruned once every event in them is older than the
                # oldest retained snapshot — a standby restoring that
                # snapshot must still replay to the tip
                ckpt_dir = self.checkpoint_dir

                def guard():
                    from kme_tpu.runtime import checkpoint as ck

                    return ck.oldest_retained_offset(ckpt_dir)
            j = Journal(j, rotate_bytes=rb, fsync=self._journal_fsync,
                        rotate_keep=self._journal_keep,
                        retention_guard=guard)
        self.journal = j
        if j is not None and resumed:
            j.rewind_to_offset(self.offset)
        # journal-side corruption drill (KME_AUDIT_TAMPER=journal_fill_qty):
        # one-shot, bumps the first journaled fill's taker quantity in a
        # COPY of the output line groups — the journal then LIES about a
        # batch while MatchOut stays untouched, which is exactly the
        # divergence class `kme-xray --bisect` must pin to a batch (the
        # auditor, a journal observer, trips on the same tampered events
        # and its repro dump carries the ready-to-run bisect line)
        self._journal_tamper = None
        self._tampered_batch = None
        tamper_env = os.environ.get("KME_AUDIT_TAMPER", "")
        if j is not None and tamper_env.startswith("journal_fill_qty"):
            from kme_tpu import opcodes as op
            import json as _json

            # "journal_fill_qty@K" arms the tamper from the K-th
            # journaled batch on (default 0) — so the bisect drill has
            # a non-trivial prefix of clean batches to rule out
            _, _, at_s = tamper_env.partition("@")
            arm_batch = int(at_s) if at_s.isdigit() else 0
            done = []
            seen = [0]     # record_batch calls == journal batch ids

            def line_tamper(out):
                b = seen[0]
                seen[0] += 1
                if done or b < arm_batch:
                    return out
                for gi, grp in enumerate(out):
                    if len(grp) < 4:   # no fill pairs (IN + result echo)
                        continue
                    for k in range(1, len(grp) - 1, 2):
                        key, _, val = grp[k + 1].partition(" ")
                        try:
                            tk = _json.loads(val)
                        except ValueError:
                            continue
                        if tk.get("action") not in (op.BOUGHT, op.SOLD):
                            continue   # not a fill-pair taker echo
                        tk["size"] = int(tk["size"]) + 1
                        new = list(grp)
                        new[k + 1] = (f"{key} "
                                      f"{_json.dumps(tk, separators=(',', ':'))}")
                        out = list(out)
                        out[gi] = new
                        done.append(True)
                        self._tampered_batch = b
                        return out
                return out

            self._journal_tamper = line_tamper
        self._init_profiling(resumed)
        self._init_watch(resumed)
        if not self._audit_arg:
            return
        if self._compat != "fixed":
            print("kme-serve: --audit needs fixed-mode money semantics; "
                  "auditing disabled for compat=java", file=sys.stderr)
            return
        if j is None:
            raise ValueError("--audit requires --journal-out (the "
                             "auditor replays the journal stream)")

        def on_violation(violations, dump):
            self.degraded = violations[0]["kind"]
            where = f" (repro: {dump})" if dump else ""
            print(f"kme-serve: AUDIT VIOLATION {violations[0]}{where}",
                  file=sys.stderr)

        self.auditor = InvariantAuditor(
            registry=self.telemetry, repro_dir=self._audit_repro_dir,
            on_violation=on_violation,
            checkpoint_ref=self.checkpoint_dir,
            journal_ref=getattr(j, "path", None),
            log_ref=getattr(self.broker, "_persist_dir", None))
        if resumed and self._session is not None:
            self.auditor.seed(self._session.export_state(),
                              self._session.histograms())
        # deliberate-corruption hook for end-to-end violation tests:
        # KME_AUDIT_TAMPER=fill_qty bumps the first journaled fill's
        # quantity by one, which must trip the auditor
        if os.environ.get("KME_AUDIT_TAMPER") == "fill_qty":
            done = []

            def tamper(events):
                if not done:
                    for ev in events:
                        if ev.get("e") == "fill":
                            ev["qty"] += 1
                            done.append(True)
                            break
                return events

            self.auditor.tamper = tamper
        j.observers.append(self.auditor.observe)

    def _init_watch(self, resumed: bool) -> None:
        """Live watchpoint wiring (ISSUE 17). Predicates evaluate
        inline at the batch barrier — directly against the serving
        OracleEngine when that IS the engine (zero-derivation, the
        kme-bench prof 3% budget), else against an auditor-shaped
        shadow ledger fed from the batch's own (untampered) output
        lines. Both are pure functions of exported state, so two
        seeded runs fire identical (offset, predicate) hit sets. Hits
        write bounded TriggerCapture-style captures into --capture-dir
        carrying the offset, the batch's slow-order trace exemplars
        and the `kme-xray` one-liner that reproduces the hit
        offline."""
        self.watch = None
        if not self._watch_arg:
            return
        if self._compat != "fixed":
            print("kme-serve: --watch needs fixed-mode money "
                  "semantics; watchpoints disabled for compat=java",
                  file=sys.stderr)
            return
        from kme_tpu.telemetry.xray import WatchEngine

        repro = {"log_dir": getattr(self.broker, "_persist_dir", None),
                 "topic": self.topic_in,
                 "checkpoint_dir": self.checkpoint_dir}
        self.watch = WatchEngine(
            self._watch_arg, out_dir=self._capture_dir,
            registry=self.telemetry, repro=repro)
        if resumed:
            state = None
            if self._session is not None:
                state = self._session.export_state()
            elif self._oracle is not None and not self._oracle.java:
                state = self._oracle.export_state()
            if state is not None:
                self.watch.seed(state)
            else:
                print("kme-serve: --watch cannot seed its shadow from "
                      "a resumed native engine; watchpoints disabled",
                      file=sys.stderr)
                self.watch = None

    def _init_profiling(self, resumed: bool) -> None:
        """Continuous profiling & history wiring (ISSUE 16): the TSDB
        heartbeat feed, the sampling host profiler, and the SLO/p99
        trigger capture. All additive: a failure to open the history
        store degrades the observability surface, never the engine."""
        if self._tsdb_arg is not None:
            from kme_tpu.telemetry.tsdb import TSDB

            source = ("follower" if self.follower else "serve")
            if self.group_count > 1:
                source = f"{source}.g{self.group_id}"
            try:
                self.tsdb = TSDB(self._tsdb_arg, source=source)
            except (OSError, ValueError) as e:
                print(f"kme-serve: TSDB disabled ({e})", file=sys.stderr)
            if self.tsdb is not None and not resumed:
                # no checkpoint cursor to continue: adopt the store's
                # high-water mark so a plain restart keeps appending
                # instead of deduping against its own history
                self.sample_seq = max(self.sample_seq,
                                      self.tsdb.next_seq())
        if self._profile_arg:
            from kme_tpu.telemetry.profiler import StageProfiler

            self.profiler = StageProfiler(registry=self.telemetry)
            self.profiler.start()
        if self._capture_dir is not None:
            from kme_tpu.telemetry.profiler import TriggerCapture

            self.capture = TriggerCapture(
                self._capture_dir, p99_us=self._capture_p99_us,
                registry=self.telemetry)

    def close(self) -> None:
        """Flush + close the flight recorder (serve shutdown path)."""
        if getattr(self, "_pipe", None):
            self._drain_pipeline()
        if getattr(self, "profiler", None) is not None:
            self.profiler.stop()
        if getattr(self, "_profile_artifact", None) is not None:
            from kme_tpu.telemetry.profiler import (device_plane,
                                                    write_transfer_artifact)

            try:
                # a session-less engine (oracle) still records the
                # host plane: backend + measured H2D bandwidth
                plane = device_plane(session=self._session)
                write_transfer_artifact(self._profile_artifact, plane)
                print(f"kme-serve: transfer/compute artifact written to "
                      f"{self._profile_artifact}", file=sys.stderr)
            except (OSError, ValueError) as e:
                print(f"kme-serve: transfer artifact failed ({e})",
                      file=sys.stderr)
        if getattr(self, "tsdb", None) is not None:
            self.tsdb.close()
        if getattr(self, "events", None) is not None:
            self.events.close()
        if getattr(self, "journal", None) is not None:
            self.journal.close()

    def _init_telemetry(self) -> None:
        """The service's metrics surface (/metrics, heartbeat). Session
        engines already own a Registry — share it so engine counters,
        histograms and service counters expose through ONE endpoint;
        host-only engines (native/oracle) get a service-local one.

        Supervision provenance rides in via environment: kme-supervise
        stamps each incarnation with its restart ordinal and the wall
        time of the failure it is recovering from, so restarts_total
        and recovery_seconds surface on THIS process's /metrics."""
        import os
        import time

        from kme_tpu.telemetry import Registry

        self.telemetry = (self._session.telemetry
                          if self._session is not None else Registry())
        try:
            ordinal = int(os.environ.get("KME_RESTART_ORDINAL", "0"))
        except ValueError:
            ordinal = 0
        self.telemetry.gauge("restarts_total").set(ordinal)
        failed_at = os.environ.get("KME_FAILED_AT")
        if failed_at:
            try:
                self.telemetry.gauge("recovery_seconds").set(
                    round(max(0.0, self.clock.time() - float(failed_at)),
                          3))
            except ValueError:
                pass
        self._init_latency()

    def _init_latency(self) -> None:
        """End-to-end latency attribution: one always-on streaming
        quantile histogram per pipeline stage (telemetry/registry.py
        LatencyHistogram — O(1) memory, lock-consistent snapshots).

        Stage boundaries, all measured from the broker-admission stamp
        (Record.ats — the INTENDED start, so queueing under overload
        shows up as latency instead of being coordinated-omission'd
        away):
          ingress — admission -> the serve loop fetches the record
          plan    — host batch planning (session plan_s delta, charged
                    to every order in the batch)
          device  — dispatch + device fetch (dispatch_s + fetch_s)
          produce — MatchOut produce wall time for the batch
          e2e     — admission -> the batch's outputs are visible
          consume — admission -> a consumer's fetch delivers the
                    MatchOut record (observed broker-side via
                    deliver_observer, since serve hosts the broker)
        """
        from kme_tpu.telemetry import PhaseTimer

        t = self.telemetry
        self._lat = {
            s: t.latency(f"lat_{s}", h) for s, h in (
                ("ingress", "broker admission to serve-loop fetch"),
                ("plan", "host batch planning"),
                ("device", "device dispatch + fetch"),
                ("produce", "MatchOut produce wall time"),
                ("e2e", "broker admission to produce visible"),
                ("consume", "broker admission to consumer delivery"),
            )}
        if self.topic_xfer is not None:
            self._lat["transfer"] = t.latency(
                "transfer_rtt", "cross-shard transfer leg: durable "
                "stamped produce to the group Xfer topic")
        # serve-side spans land on their own trace track when a
        # TraceRecorder is installed (kme-serve --trace-out)
        self._ptimer = PhaseTimer(track="serve")
        self._batch_ordinal = 0
        self._last_produce_s = 0.0
        self._phase_snap = {}
        # slowest recent orders, worst first: published as registry
        # exemplars so a cluster p99 outlier (kme-agg) resolves to a
        # concrete waterfall (kme-trace --order AID:OID)
        self._slow: list = []
        if self._slo_arg is not None:
            from kme_tpu.telemetry.slo import SLO

            self.slo = SLO(t, **self._slo_arg)
        # consume-stage visibility: serve hosts the broker, so consumer
        # receipt of MatchOut records is observable in-process
        if getattr(self.broker, "deliver_observer", None) is None \
                and hasattr(self.broker, "deliver_observer"):
            lat_consume = self._lat["consume"]
            topic_out = self.topic_out

            def _on_deliver(topic, recs, now_us):
                if topic != topic_out:
                    return
                for r in recs:
                    ats = getattr(r, "ats", None)
                    if ats is not None:
                        lat_consume.observe(max(0, now_us - ats) * 1e-6)

            self.broker.deliver_observer = _on_deliver

    _EXEMPLARS = 8

    def _stamp_orders(self, offs, oids, aids, atss, fetch_us, done_us,
                      plan_us, dev_us, prod_us, batch) -> None:
        """Per-order stage attribution, shared by the serial and
        pipelined collect paths: journal "lat" stamps, "span" events
        when tracing is on (--trace-spans), and the slow-order exemplar
        surface. Span bounds are contiguous from the admission stamp —
        the exact layout telemetry/dtrace.py synthesizes from "lat"
        events, so traced and untraced journals stitch identically.
        Span identity is local_tid(group, broker offset): pure durable
        identity, so a crash-replay re-emits the SAME ids and the
        stitcher dedups the overlap by (group, off, kind)."""
        n = len(offs)
        if not n:
            return
        from kme_tpu.telemetry.dtrace import local_tid

        g = self.group_id
        if self.journal is not None:
            self.journal.record_latency(
                [{"off": offs[i], "oid": oids[i],
                  "in_us": (max(0, fetch_us - atss[i])
                            if atss[i] is not None else 0),
                  "plan_us": plan_us, "dev_us": dev_us,
                  "prod_us": prod_us,
                  "e2e_us": (max(0, done_us - atss[i])
                             if atss[i] is not None else 0)}
                 for i in range(n)], batch=batch)
            if self.trace_spans:
                spans = []
                for i in range(n):
                    t = atss[i] if atss[i] is not None else fetch_us
                    tid = local_tid(g, offs[i])
                    for kind, dur in (
                            ("ingress", (max(0, fetch_us - atss[i])
                                         if atss[i] is not None
                                         else 0)),
                            ("plan", plan_us), ("device", dev_us),
                            ("produce", prod_us)):
                        spans.append(
                            {"kind": kind, "g": g, "off": offs[i],
                             "oid": oids[i], "aid": aids[i],
                             "tid": tid, "ptid": 0, "t0": t,
                             "t1": t + dur, "li": -1})
                        t += dur
                self.journal.record_spans(spans, batch=batch)
        cap = self._EXEMPLARS
        floor = (self._slow[-1]["e2e_us"]
                 if len(self._slow) >= cap else -1)
        changed = False
        for i in range(n):
            if atss[i] is None:
                continue
            e2e = max(0, done_us - atss[i])
            if e2e > floor or len(self._slow) < cap:
                self._slow.append(
                    {"tid": local_tid(g, offs[i]), "off": offs[i],
                     "oid": oids[i], "aid": aids[i], "g": g,
                     "e2e_us": e2e})
                changed = True
        if changed:
            self._slow.sort(key=lambda x: -x["e2e_us"])
            del self._slow[cap:]
            self.telemetry.set_exemplars(self._slow)

    # ------------------------------------------------------------------
    # durability: snapshot at batch boundaries, resume = load + replay
    # the MatchIn tail from the snapshot offset (at-least-once, like the
    # reference with exactly-once commented out — KProcessor.java:29)

    def _make_seq_session(self):
        from kme_tpu.runtime.seqsession import SeqSession

        return SeqSession(self._seq_cfg())

    def _seq_cfg(self):
        from kme_tpu.engine import seq as SQ

        slots = self._req_slots
        if slots % 128 != 0:
            raise ValueError(
                f"the seq engine needs slots % 128 == 0, got {slots}")
        return SQ.SeqConfig(
            lanes=self._req_symbols, slots=slots,
            accounts=-(-self._req_accounts // 128) * 128,
            max_fills=self._req_max_fills, hbm_books=slots > 512,
            compat=self._compat)

    def _try_resume(self, engine: str, compat: str, shards: int,
                    width: int) -> bool:
        from kme_tpu.runtime import checkpoint as ck

        if engine == "seq":
            if compat == "java":
                # the previous incarnation may have DEGRADED to the
                # native engine mid-stream (a barrier left the java
                # device surface, _degrade_to_native) and checkpointed
                # there — the NEWEST snapshot across kinds wins; the
                # .npz offsets are listed WITHOUT restoring so the
                # common degraded-restart path never pays the device
                # import
                seq_snaps = ck.list_snapshots(self.checkpoint_dir)
                seq_off = seq_snaps[0][0] if seq_snaps else -1
                nat, noff = ck.load_native(self.checkpoint_dir)
                if nat is not None and nat.java and noff > seq_off:
                    self._native = nat
                    self.offset = self._last_ckpt_offset = noff
                    print(f"kme-serve: resumed DEGRADED (native) "
                          f"java continuation at offset {noff}",
                          file=sys.stderr)
                    return True
            ses, offset = ck.load_seq_session(self.checkpoint_dir,
                                              self._seq_cfg())
            if ses is None:
                return False
            self._session = ses
        elif engine == "lanes":
            # elastic restore onto the REQUESTED topology (snapshots are
            # canonical across shards/width)
            ses, offset = ck.load_session(self.checkpoint_dir,
                                          shards=shards, width=width)
            if ses is None:
                return False
            want = {"lanes": self._req_symbols, "accounts": self._req_accounts,
                    "slots": self._req_slots, "max_fills": self._req_max_fills}
            have = {k: getattr(ses.cfg, k) for k in want}
            if want != have:
                raise ValueError(
                    f"snapshot in {self.checkpoint_dir} has capacity "
                    f"config {have}, but {want} was requested — capacity "
                    f"changes need a state migration, not a resume")
            self._session = ses
        elif engine == "native":
            nat, offset = ck.load_native(self.checkpoint_dir)
            if nat is None:
                return False
            self._check_resume_compat(nat, compat)
            if not nat.java:
                want = (self._req_slots, self._req_max_fills)
                have = (nat.book_slots, nat.max_fills)
                if want != have:
                    raise ValueError(
                        f"snapshot in {self.checkpoint_dir} has envelope "
                        f"(slots, max_fills)={have}, but {want} was "
                        f"requested — capacity changes need a state "
                        f"migration, not a resume")
            self._native = nat
        else:
            ora, offset = ck.load_oracle(self.checkpoint_dir)
            if ora is None:
                return False
            self._check_resume_compat(ora, compat)
            self._oracle = ora
        self.offset = self._last_ckpt_offset = offset
        print(f"kme-serve: resumed from snapshot at offset {offset}",
              file=sys.stderr)
        return True

    def _check_resume_compat(self, engine_obj, compat: str) -> None:
        snap_compat = "java" if engine_obj.java else "fixed"
        if snap_compat != compat:
            raise ValueError(
                f"snapshot in {self.checkpoint_dir} was taken with "
                f"compat={snap_compat!r}, but compat={compat!r} was "
                f"requested")

    def _maybe_checkpoint(self) -> None:
        if self.checkpoint_dir is None or self.follower:
            # a follower shares the leader's checkpoint dir read-only:
            # writing snapshots from two processes would race the prune
            return
        if self.offset - self._last_ckpt_offset < self.checkpoint_every:
            return
        self.checkpoint()

    def checkpoint(self) -> None:
        """Snapshot engine state + input offset (batch boundary)."""
        from kme_tpu.runtime import checkpoint as ck

        if getattr(self, "_pipe", None):
            # a snapshot must capture engine state at a committed
            # offset boundary — collect every in-flight batch first
            self._drain_pipeline()
        # make the input log durable BEFORE committing an offset into it:
        # the snapshot is fsync'd, so without this a power loss could
        # leave an offset addressing MatchIn records the OS never wrote
        # (resume would silently skip input)
        sync = getattr(self.broker, "sync", None)
        if sync is not None:
            from kme_tpu.bridge.broker import BrokerError

            try:
                sync()
            except (BrokerError, OSError) as e:
                # OSError covers the in-process broker's own fsync
                # failing (disk full / EIO) — defer, don't die
                print(f"kme-serve: broker sync failed before checkpoint "
                      f"({e}); snapshot deferred", file=sys.stderr)
                return
        # the heartbeat sample cursor rides EVERY snapshot (not just
        # exactly-once leaders'): a resumed service continues the TSDB
        # sequence so replayed heartbeat samples dedup on ingestion
        extra = {"sample_seq": self.sample_seq}
        if self.epoch is not None:
            from kme_tpu.bridge import lease
            from kme_tpu.bridge.broker import BrokerFenced

            if faults.should("lease.steal", offset=self.offset):
                # split-brain drill: another incarnation grabs the next
                # epoch (and, like any real new leader, fences us at
                # the broker)
                stolen = lease.steal(self.checkpoint_dir)
                fence = getattr(self.broker, "fence", None)
                if fence is not None:
                    fence(stolen)
                print(f"kme-faults: lease stolen (epoch {stolen}) at "
                      f"offset {self.offset}", file=sys.stderr)
            cur = lease.current_epoch(self.checkpoint_dir)
            if cur > self.epoch:
                # self-fence before writing anything: a newer leader
                # owns the stream; our snapshot would roll ITS state
                # machine back
                raise BrokerFenced(
                    f"fenced: leader epoch {self.epoch} superseded by "
                    f"{cur}; refusing to checkpoint")
            extra.update(epoch=self.epoch, out_seq=self.out_seq)
            if self.topic_xfer is not None:
                # the pending_reserve ledger rides the snapshot so a
                # resumed leader reports continuous cross-shard totals;
                # the transfer LEGS themselves regenerate from MatchIn
                # replay and dedup on their (epoch, out_seq) stamps
                extra["pending_reserve"] = dict(self._xfer)
        if self._session is not None:
            from kme_tpu.runtime.seqsession import SeqSession

            if isinstance(self._session, SeqSession):
                ck.save_seq_session(self.checkpoint_dir, self._session,
                                    self.offset, keep=self.checkpoint_keep,
                                    extra=extra)
            else:
                ck.save_session(self.checkpoint_dir, self._session,
                                self.offset, keep=self.checkpoint_keep,
                                extra=extra)
        elif self._native is not None:
            ck.save_native(self.checkpoint_dir, self._native, self.offset,
                           keep=self.checkpoint_keep, extra=extra)
        else:
            ck.save_oracle(self.checkpoint_dir, self._oracle, self.offset,
                           keep=self.checkpoint_keep, extra=extra)
        self._last_ckpt_offset = self.offset
        if self.journal is not None:
            # the journal is best-effort relative to the broker log, but
            # a snapshot is a natural durability point for it too
            self.journal.flush()
        if self.auditor is not None and self._session is not None:
            # checkpoint-cadence cross-check: shadow ledger vs the
            # engine's exported stores + device histograms
            self.auditor.check_engine(self._session.export_state(),
                                      self._session.histograms())

    # ------------------------------------------------------------------

    def _parse(self, value: str):
        from kme_tpu.runtime.sequencer import EnvelopeError
        from kme_tpu.wire import parse_order

        try:
            m = parse_order(value)
            # the Jackson envelope: price/size are Java int fields, so
            # out-of-int32 values kill the reference's deserializer
            # (KProcessor.java:513-517) exactly like non-JSON input —
            # same drop/strict policy, for every engine
            if not (-2**31 <= m.price < 2**31 and -2**31 <= m.size < 2**31):
                raise EnvelopeError(
                    f"price/size outside int32 (price={m.price}, "
                    f"size={m.size})")
            return m
        except (ValueError, EnvelopeError):
            if self.strict:
                raise
            print(f"kme-serve: dropping malformed record: {value[:120]!r}",
                  file=sys.stderr)
            return None

    def step(self, timeout: float = 0.5) -> int:
        """Poll once: fetch up to `batch` records, process, produce the
        record stream. Returns the number of input records consumed."""
        if self._pipe is not None and self._session is not None:
            return self._step_pipelined(timeout)
        from kme_tpu.bridge.broker import BrokerError

        try:
            recs = self.broker.fetch(self.topic_in, self.offset, self.batch,
                                     timeout=timeout)
        except BrokerError:
            # topics not provisioned yet — keep polling, like a Streams
            # app waiting for its source topic
            self.clock.sleep(min(timeout, 0.05))
            return 0
        if not recs:
            return 0
        return self._process_batch(recs)

    def _process_batch(self, recs) -> int:
        """Serial batch processing: parse, engine, produce, commit —
        the per-record authority every engine/compat combination
        supports (the pipelined path above delegates here for batches
        with malformed or out-of-envelope records)."""
        import time as _t

        fetch_us = self.clock.time_us()
        lat = self._lat
        msgs, offs, drops, atss = [], [], [], []
        for r in recs:
            ats = getattr(r, "ats", None)
            if ats is not None:
                # ingress = broker admission -> this fetch; per-record,
                # from the intended-start stamp
                lat["ingress"].observe(max(0, fetch_us - ats) * 1e-6)
            m = self._parse(r.value)
            if m is not None:
                msgs.append(m)
                offs.append(r.offset)
                atss.append(ats)
            else:
                drops.append((-1, r.offset))
        out = reasons = None
        self._batch_ordinal += 1
        self._last_produce_s = 0.0
        phases = getattr(self._session, "phases", None)
        p0 = dict(phases) if phases is not None else {}
        t_engine0 = _t.perf_counter()
        if msgs:
            if self._native is not None:
                with self._ptimer.phase("serve_engine"):
                    self._flow("s")
                    out = self._native_produce(msgs)
            elif self._session is not None:
                try:
                    with self._ptimer.phase("serve_engine"):
                        self._flow("s")
                        out = self._session.process_wire(msgs)
                except Exception as e:
                    from kme_tpu.runtime.seqsession import \
                        UnsupportedJavaOp

                    if not isinstance(e, UnsupportedJavaOp):
                        raise
                    # a java-mode stream left the device surface
                    # (barrier / negative-sid symbol, COMPAT.md): the
                    # router raises BEFORE any device mutation, so the
                    # session's state converts losslessly to the native
                    # engine (runtime/javasnap.py) and serving
                    # continues there — the batch replays on the
                    # native engine from the same state
                    self._degrade_to_native(str(e))
                    out = self._native_produce(msgs)
                else:
                    reasons = self._session.last_reasons
                    self._produce_lines(out)
            else:
                from kme_tpu.wire import dumps_order

                with self._ptimer.phase("serve_engine"):
                    self._flow("s")
                    out = [[f"{rec.key} {dumps_order(rec.value)}"
                            for rec in self._oracle.process(m)]
                           for m in msgs]
                self._produce_lines(out)
            if self.annotate_rejects and out is not None:
                self._produce_rej_annotations(out, reasons)
        # -- latency attribution: charge the batch's stage wall times to
        # every order in it (per-order quantiles), e2e from each
        # record's own admission stamp
        done_us = self.clock.time_us()
        n = len(msgs)
        plan_d = dev_d = 0.0
        if n:
            if phases is not None:
                p1 = self._session.phases if self._session is not None \
                    else p0
                plan_d = p1.get("plan_s", 0.0) - p0.get("plan_s", 0.0)
                dev_d = (p1.get("dispatch_s", 0.0) + p1.get("fetch_s", 0.0)
                         - p0.get("dispatch_s", 0.0) - p0.get("fetch_s", 0.0))
            else:
                # host engines (native/oracle) have no plan/device
                # split; the whole engine wall is "device" time
                dev_d = max(0.0, _t.perf_counter() - t_engine0
                            - self._last_produce_s)
            if plan_d > 0:
                lat["plan"].observe(plan_d, n)
            if dev_d > 0:
                lat["device"].observe(dev_d, n)
                self.telemetry.gauge(
                    "device_ms_per_batch",
                    "device wall time of the last batch").set(
                    round(dev_d * 1e3, 3))
            if self._last_produce_s > 0:
                lat["produce"].observe(self._last_produce_s, n)
            e2e_hot = 0.0
            for ats in atss:
                if ats is not None:
                    d = max(0, done_us - ats) * 1e-6
                    lat["e2e"].observe(d)
                    if d > e2e_hot:
                        e2e_hot = d
            ctl = getattr(self.broker, "overload", None)
            if ctl is not None and e2e_hot > 0:
                # admission-to-produce feed for the degradation state
                # machine (latency can trip shedding before backlog does)
                ctl.observe_latency(e2e_hot)
        if self.journal is not None and (out or drops):
            jout = out or []
            if self._journal_tamper is not None:
                jout = self._journal_tamper(jout)
            self.journal.record_batch(jout, reasons=reasons,
                                      offsets=offs[:len(out or [])],
                                      drops=drops)
        if n:
            # full batch wall per order (what the order EXPERIENCED —
            # same convention as the histograms above), not an
            # amortized per-order share
            self._stamp_orders(
                offs[:n], [int(m.oid) for m in msgs],
                [int(m.aid) for m in msgs], atss, fetch_us, done_us,
                int(plan_d * 1e6), int(dev_d * 1e6),
                int(self._last_produce_s * 1e6),
                batch=self._batch_ordinal)
        if self.watch is not None and n:
            # batch barrier: the serving oracle IS the deterministic
            # state machine, so predicates read it directly — no
            # lifecycle re-derivation, no shadow ledger, and never the
            # journal-tamper copy. After _stamp_orders so a firing
            # capture embeds this batch's trace exemplars. Drop-only
            # batches change no state and cannot transition a
            # predicate, so they are skipped.
            self.watch.observe_engine(self._oracle, offs[n - 1],
                                      exemplars=self._slow)
        # batch-boundary commit (H5): offsets advance only after the
        # outputs for the whole batch are on MatchOut
        self.offset = recs[-1].offset + 1
        # crash window the chaos harness targets: outputs are on
        # MatchOut but the snapshot has not caught up — recovery MUST
        # replay from the last checkpoint and reproduce these bytes.
        # (Leader-only: a follower tails the raw input log and can run
        # ahead of the leader, so it must not consume the kill budget.)
        if not self.follower:
            faults.kill_now("serve.kill", offset=self.offset)
        self._maybe_checkpoint()
        self._commit_watermark()
        self._publish_batch(len(recs), len(recs) - len(msgs))
        return len(recs)

    # -- pipelined serving (H5): submit N+1 while N runs on the device

    def _parse_batch(self, recs):
        """Columnar parse of a fetched batch (native kme_parse when
        built). Returns a WireBatch when EVERY record parses clean and
        passes the reference's int32 price/size envelope — the hot
        case; None sends the batch through the per-record _parse path
        (whose drop/strict policy is the authority for bad input)."""
        import numpy as np

        from kme_tpu.wire import WireBatch

        try:
            payload = b"\n".join(
                v if isinstance(v, bytes) else v.encode()
                for v in (r.value for r in recs))
            wb = WireBatch.parse_buffer(payload)
        except (ValueError, OverflowError, UnicodeEncodeError,
                AttributeError):
            return None
        if wb.n != len(recs):
            return None  # embedded newlines / empty values
        lim = 1 << 31
        if not (np.all(wb.price >= -lim) and np.all(wb.price < lim)
                and np.all(wb.size >= -lim) and np.all(wb.size < lim)):
            return None
        return wb

    def _step_pipelined(self, timeout: float = 0.5) -> int:
        """Poll once in pipelined mode: parse + plan + DISPATCH this
        batch without waiting on the device, then retire the oldest
        in-flight batch once the window exceeds `pipeline` — batch
        N+1's host work runs under batch N's device step. The fetch
        cursor runs ahead of the committed offset by the in-flight
        window; self.offset still advances only at collect time, so
        the at-least-once replay contract (H5 batch-boundary commit)
        is unchanged."""
        from kme_tpu.bridge.broker import BrokerError

        fetch_off = self._pipe[-1][0] if self._pipe else self.offset
        try:
            recs = self.broker.fetch(self.topic_in, fetch_off, self.batch,
                                     timeout=timeout)
        except BrokerError:
            self.clock.sleep(min(timeout, 0.05))
            return 0
        if not recs:
            # idle input: finish the in-flight window so output
            # visibility and offsets never stall behind an empty poll
            self._drain_pipeline()
            return 0
        import time as _t

        wb = self._parse_batch(recs)
        if wb is None:
            # malformed / out-of-envelope records: drain, then run the
            # batch through the exact per-record path (drops, strict)
            self._drain_pipeline()
            return self._process_batch(recs)
        fetch_us = self.clock.time_us()
        lat = self._lat
        atss = []
        for r in recs:
            ats = getattr(r, "ats", None)
            atss.append(ats)
            if ats is not None:
                lat["ingress"].observe(max(0, fetch_us - ats) * 1e-6)
        end_off = recs[-1].offset + 1
        if (self.checkpoint_dir is not None and not self.follower
                and self._pipe
                and end_off - self._last_ckpt_offset
                >= self.checkpoint_every):
            # a due snapshot needs a drained pipeline (engine state at
            # a committed offset boundary); drain BEFORE submitting so
            # the cadenced checkpoint fires at this batch's collect
            self._drain_pipeline()
        self._batch_ordinal += 1
        phases = self._session.phases
        p0 = dict(phases)
        with self._ptimer.phase("serve_engine"):
            self._flow("s")
            handle = self._session.submit(wb)
        plan_d = phases.get("plan_s", 0.0) - p0.get("plan_s", 0.0)
        self._pipe.append((end_off, handle, wb,
                           [r.offset for r in recs], atss, fetch_us,
                           plan_d, self._batch_ordinal))
        while len(self._pipe) > self.pipeline:
            self._collect_one()
        return len(recs)

    def _collect_one(self) -> None:
        """Retire the oldest in-flight batch: fetch + reconstruct its
        outputs, produce, journal, and only THEN advance the committed
        offset. Checkpoints wait for an empty pipeline: a snapshot must
        pair engine state with an offset whose every predecessor is
        visible on MatchOut."""
        import time as _t

        (end_off, handle, wb, offs, atss, fetch_us, plan_d,
         ordinal) = self._pipe.popleft()
        lat = self._lat
        self._last_produce_s = 0.0
        phases = self._session.phases
        p0 = dict(phases)
        with self._ptimer.phase("serve_engine"):
            buf, line_off, msg_lines = self._session.collect(handle)
        reasons = self._session.last_reasons
        # device attribution under pipelining: what the batch WAITED at
        # fetch time (overlapped device work the host never sees is the
        # point of the pipeline)
        dev_d = phases.get("fetch_s", 0.0) - p0.get("fetch_s", 0.0)
        self._produce_buffer(buf, line_off, ordinal)
        done_us = self.clock.time_us()
        n = wb.n
        if plan_d > 0:
            lat["plan"].observe(plan_d, n)
        if dev_d > 0:
            lat["device"].observe(dev_d, n)
            self.telemetry.gauge(
                "device_ms_per_batch",
                "device wall time of the last batch").set(
                round(dev_d * 1e3, 3))
        if self._last_produce_s > 0:
            lat["produce"].observe(self._last_produce_s, n)
        e2e_hot = 0.0
        for ats in atss:
            if ats is not None:
                d = max(0, done_us - ats) * 1e-6
                lat["e2e"].observe(d)
                if d > e2e_hot:
                    e2e_hot = d
        ctl = getattr(self.broker, "overload", None)
        if ctl is not None and e2e_hot > 0:
            ctl.observe_latency(e2e_hot)
        out = None
        if (self.journal is not None or self.watch is not None) and n:
            out = self._lines_of(buf, line_off, msg_lines)
        if self.journal is not None and n:
            jout = out
            if self._journal_tamper is not None:
                jout = self._journal_tamper(jout)
            self.journal.record_batch(jout, reasons=reasons,
                                      offsets=offs, drops=[])
        if n:
            self._stamp_orders(
                offs, wb.oid.tolist(), wb.aid.tolist(), atss,
                fetch_us, done_us, int(plan_d * 1e6),
                int(dev_d * 1e6), int(self._last_produce_s * 1e6),
                batch=ordinal)
        if self.watch is not None and out:
            self.watch.observe_lines(out, reasons=reasons,
                                     offsets=offs, drops=[],
                                     exemplars=self._slow)
        self.offset = end_off
        if not self.follower:
            faults.kill_now("serve.kill", offset=self.offset)
        if not self._pipe:
            # engine state now equals the committed offset — the only
            # point where a snapshot is coherent under pipelining
            self._maybe_checkpoint()
        self._commit_watermark()
        self._publish_batch(n, 0)

    def _drain_pipeline(self) -> None:
        """Collect every in-flight batch (idle input, a slow-path
        batch, a due checkpoint, shutdown)."""
        while self._pipe:
            self._collect_one()

    @staticmethod
    def _lines_of(buf, line_off, msg_lines):
        """Reconstruction buffer -> per-message line lists (the journal
        and annotation surfaces still speak lines)."""
        text = buf.decode("ascii")
        lo = line_off.tolist()
        out, li = [], 0
        for nl in msg_lines.tolist():
            out.append([text[lo[li + k]:lo[li + k + 1]]
                        for k in range(nl)])
            li += nl
        return out

    def _produce_buffer(self, buf, line_off, ordinal=None) -> None:
        """Produce a reconstructed record buffer line by line — the
        collect-side twin of _produce_lines (same stamping, retry and
        flow-arrow semantics)."""
        import time as _t

        t0 = _t.perf_counter()
        with self._ptimer.phase("serve_produce"):
            self._flow("f", ordinal)
            text = buf.decode("ascii")
            lo = line_off.tolist()
            for i in range(len(lo) - 1):
                key, _, value = text[lo[i]:lo[i + 1]].partition(" ")
                self._produce_out(key, value)
        self._last_produce_s += _t.perf_counter() - t0

    def _publish_batch(self, nrecs: int, ndropped: int) -> None:
        """Per-batch service counters + a rate-limited engine refresh.
        Runs on the POLL THREAD only: the engine refresh touches device
        arrays, which the heartbeat/HTTP threads must never do — they
        read registry snapshots."""
        t = self.telemetry
        t.counter("service_batches").inc()
        t.counter("service_records").inc(nrecs)
        t.counter("service_dropped").inc(ndropped)
        t.gauge("service_offset").set(self.offset)
        if faults.active():
            t.gauge("faults_injected").set(faults.fired_total())
        shed = getattr(self.broker, "overload_rejects", None)
        if shed is not None:
            t.gauge("overload_rejects").set(shed)
        nbin = getattr(self.broker, "wire_binary_records", None)
        if nbin is not None:
            # binary-wire adoption surface (kme-top shows a wire row
            # keyed on wire_binary_frac being present)
            njson = self.broker.wire_json_records
            total = nbin + njson
            t.gauge("wire_binary_frac",
                    "fraction of ingress records that arrived as "
                    "binary wire frames").set(
                round(nbin / total, 6) if total else 0.0)
            t.gauge("parse_ns_per_msg",
                    "mean wire-frame decode cost per binary "
                    "record (ns)").set(
                round(self.broker.wire_parse_ns / nbin) if nbin else 0)
        ov = getattr(self._session, "h2d_overlap_frac", None)
        if ov:
            # stage-transfer overlap surface (r14): fraction of H2D
            # staging wall hidden under in-flight device execution
            t.gauge("h2d_overlap_frac",
                    "fraction of host->device staging time "
                    "overlapped with device execution").set(ov)
        ctl = getattr(self.broker, "overload", None)
        if ctl is not None:
            # adaptive-controller surface (kme-top shows a degradation
            # row keyed on overload_state being present)
            t.gauge("overload_state",
                    "degradation state: 0 normal / 1 shedding / "
                    "2 draining").set(ctl.state)
            t.gauge("overload_backoff_ms",
                    "AIMD producer backoff hint carried on "
                    "rej_overload").set(ctl.backoff_ms)
            t.gauge("overload_transitions",
                    "degradation state-machine transitions").set(
                ctl.transitions)
            t.gauge("overload_fairness_sheds",
                    "class-2 sheds forced by the per-account "
                    "fairness cap").set(ctl.fairness_sheds)
            for cls in range(3):
                t.gauge(f"shed_by_class{cls}").set(
                    ctl.shed_by_class[cls])
                t.gauge(f"admitted_by_class{cls}").set(
                    ctl.admitted_by_class[cls])
            if self._shed_pending is not None:
                self._drain_shed_annotations()
        self._publish_eos_gauges()
        if self.journal is not None:
            t.gauge("journal_last_offset",
                    "input offset of the newest committed journal "
                    "record").set(self.journal.last_offset)
            t.gauge("journal_lag_bytes",
                    "bytes accepted by the journal but not yet "
                    "committed by its writer").set(self.journal.lag_bytes)
        ph = getattr(self._session, "phases", None) \
            if self._session is not None else None
        if ph:
            # host-path attribution (ISSUE: live gauges): cumulative
            # wall seconds the serve loop spent OFF the device
            plan = ph.get("plan_s", 0.0)
            recon = ph.get("recon_s", 0.0)
            t.gauge("plan_s",
                    "cumulative host planning wall (s)").set(
                round(plan, 6))
            t.gauge("recon_s",
                    "cumulative output reconstruction wall (s)").set(
                round(recon, 6))
            t.gauge("host_path_s",
                    "cumulative host-path wall: plan + "
                    "reconstruction (s)").set(round(plan + recon, 6))
        if self._pipe is not None:
            t.gauge("pipeline_depth",
                    "in-flight pipelined batches").set(len(self._pipe))
        now = self.clock.monotonic()
        if now - self._last_engine_pub >= 1.0:
            self._last_engine_pub = now
            if self._session is not None:
                self._session.metrics()   # publishes counters + gauges
                self._session.histograms()  # publishes bucket counts
            if self.slo is not None:
                # SLO degradation rides the same heartbeat channel as
                # an audit violation; the auditor's verdict wins
                self._slo_reason = self.slo.evaluate()
            if self.profiler is not None:
                self.profiler.publish(t)
            if self.capture is not None:
                # trigger-based capture: SLO burn or a p99 exemplar
                # past threshold records a bounded profile window whose
                # span ids resolve through kme-trace
                fired = self.capture.maybe_fire(self._slo_reason,
                                                t.exemplars())
                if fired:
                    print(f"kme-serve: profile capture {fired}",
                          file=sys.stderr)

    def _publish_eos_gauges(self) -> None:
        """Exactly-once observability (cheap broker-attribute reads;
        safe from the heartbeat thread too)."""
        t = self.telemetry
        for name, attr in (("dup_suppressed_total", "dup_suppressed"),
                           ("fenced_produces_total", "fenced_produces")):
            v = getattr(self.broker, attr, None)
            if v is not None:
                t.gauge(name).set(v)
        if self.epoch is not None:
            t.gauge("leader_epoch").set(self.epoch)
        if self.topic_xfer is not None:
            self._publish_group_gauges()

    def _publish_group_gauges(self) -> None:
        """Per-group scale-out surface (ISSUE 9): identity, input lag
        behind the group's own MatchIn topic, and the cross-shard
        transfer ledger. Gauges (not counters) so a resumed leader
        republishes the checkpointed totals without double counting."""
        t = self.telemetry
        gk = self.group_id
        t.gauge("group_id").set(gk)
        t.gauge("group_count").set(self.group_count)
        end = getattr(self.broker, "end_offset", None)
        if end is not None:
            from kme_tpu.bridge.broker import BrokerError

            try:
                t.gauge(f"group{gk}_lag",
                        "input records admitted to this group's "
                        "MatchIn topic but not yet applied").set(
                    max(0, end(self.topic_in) - self.offset))
            except BrokerError:
                pass    # topic not provisioned yet
        x = self._xfer
        t.gauge("cross_shard_transfers_total",
                "applied cross-shard balance-transfer legs").set(
            x["legs"])
        t.gauge("cross_shard_transfer_volume",
                "cents moved across groups (credits+debits)").set(
            x["credits"] + x["debits"])
        t.gauge("cross_shard_rejected_total").set(x["rejected"])
        t.gauge("balance_broadcasts_total").set(x["broadcasts"])

    def _produce_retry(self, topic: str, key, value,
                       stamp: bool = False) -> None:
        """Produce with bounded exponential backoff. A transport blip
        (socket reset, injected broker.produce fault) must not kill the
        serve loop mid-batch: the offset has NOT advanced yet, so a
        retry is safe — at worst the record lands twice, which the
        at-least-once contract allows and the exactly-once stamp path
        dedups broker-side. `stamp=True` marks an output-stream record:
        a leader sends it with its `(epoch, out_seq)` stamp; a follower
        only COUNTS it (the discarded produce keeps the cursor aligned
        for promotion). BrokerFenced is never retried — a newer leader
        owns the stream and this process must die so its supervisor
        restarts it under a fresh epoch."""
        from kme_tpu.bridge.broker import BrokerError, BrokerFenced

        stamped = stamp and self.epoch is not None
        counted = stamp and (stamped
                             or (self.follower and self.exactly_once))
        delay = 0.05
        for attempt in range(6):
            try:
                if stamped:
                    self.broker.produce(topic, key, value,
                                        epoch=self.epoch,
                                        out_seq=self.out_seq)
                else:
                    self.broker.produce(topic, key, value)
                if counted:
                    self.out_seq += 1
                return
            except BrokerFenced:
                raise
            except BrokerError as e:
                if attempt == 5:
                    raise
                self.telemetry.counter("broker_retries").inc()
                print(f"kme-serve: produce to {topic} failed ({e}); "
                      f"retry {attempt + 1}/5 in {delay:.2f}s",
                      file=sys.stderr)
                self.clock.sleep(delay)
                delay = min(delay * 2, 1.0)

    def _produce_out(self, key, value) -> None:
        """Route one output line: organic records go to this group's
        MatchOut stream; front-injected cross-shard lines (the
        XFER_MARK passthrough stamp in `prev` — bridge/front.py) are
        suppressed from the merged feed and land STAMPED on the
        per-group Xfer topic instead, so every applied transfer leg
        leaves one fenced `(epoch, out_seq)` row of durable dedup
        evidence. Both paths consume the same out_seq cursor, keeping
        the stamp stream deterministic across crash-replay."""
        if self._xfer_mark is not None and self._xfer_mark in value:
            self._produce_xfer(key, value)
        else:
            self._produce_retry(self.topic_out, key, value, stamp=True)

    def _produce_xfer(self, key, value) -> None:
        import json
        import time as _t

        t0 = _t.perf_counter()
        self._produce_retry(self.topic_xfer, key, value, stamp=True)
        lat = self._lat.get("transfer")
        if lat is not None:
            lat.observe(_t.perf_counter() - t0)
        if key != "OUT":
            return      # ledger counts each leg once, on its result
        try:
            msg = json.loads(value)
            action, size = int(msg["action"]), int(msg["size"])
        except (ValueError, KeyError, TypeError):
            return
        x = self._xfer
        from kme_tpu import opcodes as op

        if action == op.TRANSFER:
            x["legs"] += 1
            if size >= 0:
                x["credits"] += size
            else:
                x["debits"] -= size
        elif action == op.CREATE_BALANCE:
            x["broadcasts"] += 1
        elif action == op.REJECT:
            x["rejected"] += 1

    def _flow(self, phase: str, ordinal: Optional[int] = None) -> None:
        """Trace flow arrow endpoint for the current batch: "s" inside
        the engine span, "f" inside the produce span — Perfetto draws
        the causality arrow submit -> produce across tracks. Pipelined
        collects pass their submit-time ordinal explicitly (newer
        batches may have submitted in between)."""
        from kme_tpu.telemetry import get_tracer

        tr = get_tracer()
        if tr is not None:
            tr.flow("batch", phase,
                    self._batch_ordinal if ordinal is None else ordinal,
                    track="serve")

    def _produce_lines(self, out) -> None:
        import time as _t

        t0 = _t.perf_counter()
        with self._ptimer.phase("serve_produce"):
            self._flow("f")
            for lines in out:
                for ln in lines:
                    key, _, value = ln.partition(" ")
                    self._produce_out(key, value)
        # accumulates across the branch paths that produce more than
        # once per step (native partial + REJ annotations)
        self._last_produce_s += _t.perf_counter() - t0

    def _native_produce(self, msgs):
        # byte-faithful death handling: forward every completed
        # message's records, THEN die like the reference thread
        out, exc = self._native.process_wire_partial(msgs)
        self._produce_lines(out)
        if exc is not None:
            raise exc
        return out

    def _produce_rej_annotations(self, out, reasons) -> None:
        """Opt-in per-order reject causes as ADDITIVE "REJ"-keyed
        MatchOut records (wire.rej_record_json) — the IN/OUT stream
        stays byte-identical to the reference. Engines without exact
        codes (native/oracle) get the action heuristic."""
        import json

        from kme_tpu.wire import (REJ_UNSPECIFIED, reason_for_reject,
                                  rej_record_json)

        for i, lines in enumerate(out):
            if not lines or '"action":7,' not in lines[-1]:
                continue
            m = json.loads(lines[0].partition(" ")[2])
            code = (int(reasons[i]) if reasons is not None
                    else reason_for_reject(m["action"]))
            if code == 0:
                code = REJ_UNSPECIFIED
            self._produce_retry(self.topic_out, "REJ", rej_record_json(
                m["oid"], m["aid"], code))

    def _drain_shed_annotations(self) -> None:
        """REJ rows for controller sheds. The shed never reached the
        engine (it is a produce-time refusal), so the annotation is the
        only durable trace — it carries the observed backlog, the
        active threshold, the degradation state and the backoff hint."""
        from kme_tpu.wire import REJ_OVERLOAD, rej_record_json

        q = self._shed_pending
        while True:
            try:
                d = q.popleft()
            except IndexError:
                break
            self._produce_retry(self.topic_out, "REJ", rej_record_json(
                d.get("oid", 0), d.get("aid", 0), REJ_OVERLOAD,
                detail={"backlog": d["backlog"],
                        "threshold": d["threshold"],
                        "state": d["state"],
                        "backoff_ms": d["backoff_ms"]}))

    def _degrade_to_native(self, reason: str) -> None:
        """One-way engine degradation for java-mode streams that leave
        the device surface (COMPAT.md): the seq session's state
        converts losslessly to the native engine (runtime/javasnap.py)
        and serving continues there — the full java wire surface incl.
        barriers. Checkpoints switch to native snapshots; a restart
        resumes the degraded continuation (_try_resume)."""
        from kme_tpu.native.oracle import NativeOracleEngine, \
            native_available
        from kme_tpu.runtime.javasnap import export_seqjava, \
            to_native_dump

        if not native_available():
            raise RuntimeError(
                f"java stream left the device surface ({reason}) and "
                f"the native engine is unavailable to degrade onto — "
                f"serve this stream with engine='native' or 'oracle'")
        print(f"kme-serve: java stream left the device surface "
              f"({reason}); continuing on the native engine",
              file=sys.stderr)
        eng = NativeOracleEngine("java")
        eng.load_state(to_native_dump(export_seqjava(self._session)))
        self._native = eng
        self._session = None

    def metrics(self) -> Optional[dict]:
        """On-device counters+gauges (lanes engine; None for oracle)."""
        return self._session.metrics() if self._session is not None else None

    def run(self, max_messages: Optional[int] = None,
            idle_exit: Optional[float] = None,
            poll_timeout: float = 0.5,
            health_file: Optional[str] = None,
            health_every: float = 1.0) -> int:
        """Serve until max_messages consumed (None = forever) or the
        input topic stays idle for `idle_exit` seconds.

        health_file: heartbeat surface for the supervisor (kme-supervise)
        — a JSON snapshot {pid, time, seen, offset} atomically replaced
        every `health_every` seconds FROM A BACKGROUND THREAD, so a
        legitimately long step (first-batch XLA compile, a large
        checkpoint write) does not read as a hang; a stale mtime means
        the PROCESS froze or died (the reference delegates liveness to
        Kafka's group-membership heartbeats, KProcessor.java:59-60 via
        the Streams library)."""
        import os
        import threading
        import time

        # fault injection (tests/test_supervise.py): when
        # KME_TEST_STALL_ONCE names a flag file that does not exist yet,
        # the loop freezes (tick stops advancing) after
        # KME_TEST_STALL_AT messages while the heartbeat THREAD stays
        # alive — the exact hang shape the supervisor's stall branch
        # exists to catch. The flag file is created before freezing so
        # the restarted incarnation runs clean (stall exactly once).
        # Armed ONLY under KME_TEST_HOOKS=1: a stray KME_TEST_STALL_ONCE
        # in a production environment must never be able to wedge a
        # real deployment.
        stall_once = (os.environ.get("KME_TEST_STALL_ONCE")
                      if os.environ.get("KME_TEST_HOOKS") == "1"
                      else None)
        stall_at = int(os.environ.get("KME_TEST_STALL_AT", "100"))

        seen = 0
        beat_stop = None
        # the beater thread also runs when only a TSDB is configured
        # (health_file=None): metrics history wants the same heartbeat
        # cadence whether or not a supervisor is watching
        if health_file is not None or self.tsdb is not None:
            beat_stop = threading.Event()
            # readers (kme-agg staleness detection) need the cadence
            # to judge "hasn't advanced in 3 intervals"
            self._hb_every = float(health_every)
            state = self

            def beater():
                while not beat_stop.wait(health_every):
                    state._write_heartbeat(health_file, seen_box[0],
                                           tick_box[0])

            seen_box = [0]
            tick_box = [0]
            self._write_heartbeat(health_file, 0, 0)
            t = threading.Thread(target=beater, daemon=True)
            t.start()
        try:
            idle_since = self.clock.monotonic()
            while max_messages is None or seen < max_messages:
                n = self.step(timeout=poll_timeout)
                if beat_stop is not None:
                    # the loop TICK advances every iteration, idle or
                    # not — a frozen tick is the supervisor's hang
                    # signal (the mtime alone only proves the beater
                    # thread lives)
                    tick_box[0] += 1
                now = self.clock.monotonic()
                if n == 0:
                    if idle_exit is not None \
                            and now - idle_since >= idle_exit:
                        break
                else:
                    idle_since = now
                    seen += n
                    if beat_stop is not None:
                        seen_box[0] = seen
                if (stall_once and seen >= stall_at
                        and not os.path.exists(stall_once)):
                    open(stall_once, "w").close()
                    while True:   # frozen tick, live heartbeat thread
                        time.sleep(0.5)
                if n and not self.follower \
                        and faults.should("serve.stuck",
                                          offset=self.offset):
                    # stuck step(): the loop tick freezes while the
                    # heartbeat thread keeps the mtime fresh — exactly
                    # the hang shape the supervisor's stall branch
                    # detects (fresh mtime + frozen tick)
                    print(f"kme-faults: serve loop stuck at offset "
                          f"{self.offset}", file=sys.stderr)
                    while True:
                        time.sleep(0.5)
        finally:
            try:
                if self._pipe:
                    # in-flight batches hold committed-but-invisible
                    # work — finish them before the final heartbeat
                    self._drain_pipeline()
            finally:
                if beat_stop is not None:
                    beat_stop.set()
                    self._write_heartbeat(health_file, seen,
                                          tick_box[0], closing=True)
        return seen

    def _write_heartbeat(self, path: Optional[str], seen: int,
                         tick: int = 0, closing: bool = False) -> None:
        import json
        import os

        # refresh broker-side exactly-once counters HERE, not only on
        # the batch path: the final heartbeat after run() drains must
        # capture post-batch suppressions/fences
        self._publish_eos_gauges()
        # one monotonically increasing id per heartbeat: the TSDB uses
        # it to dedup samples replayed after a crash-resume exactly the
        # way the broker dedups (epoch, out_seq); persisted via
        # checkpoint extra so a resumed service keeps counting from
        # where the snapshot left off
        seq = self.sample_seq
        self.sample_seq = seq + 1
        snap = self.telemetry.snapshot()
        if path is None:       # TSDB-only heartbeat (no supervisor)
            self._append_tsdb(snap, seq)
            return
        # additive events-log keys (COMPAT.md): the committed-bytes
        # cursor of this process's control-plane event log. kme-agg
        # reads them to flag a recorder that froze while the heartbeat
        # itself kept advancing
        ev = getattr(self, "events", None)
        evkeys = ({"events_last_offset": ev.last_offset,
                   "events_lag_bytes": ev.lag_bytes}
                  if ev is not None else {})
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            # "metrics" is ADDITIVE — the supervisor keys
            # (pid/time/seen/offset/tick) are load-bearing
            # (tests/test_supervise.py). snapshot() only takes the
            # registry lock; safe from this background thread.
            # "closing" tells the supervisor the serve loop ended on
            # purpose (idle-exit / max-messages): the tick is frozen by
            # definition, so the stall detector must stand down while
            # the final checkpoint + teardown run.
            json.dump({"pid": os.getpid(), "time": self.clock.time(),
                       "seen": seen, "offset": self.offset,
                       "tick": tick, "closing": closing,
                       "degraded": self.degraded or self._slo_reason,
                       "role": "follower" if self.follower else "leader",
                       "epoch": self.epoch,
                       "sample_seq": seq,
                       "every": getattr(self, "_hb_every", 1.0),
                       **evkeys,
                       "metrics": snap}, f)
        os.replace(tmp, path)
        self._append_tsdb(snap, seq)

    def _append_tsdb(self, snap: dict, seq: int) -> None:
        if self.tsdb is None:
            return
        try:
            self.tsdb.append_snapshot(snap, seq)
        except OSError as e:
            # history is best-effort; the live heartbeat is not
            print(f"kme-serve: TSDB append failed: {e}",
                  file=sys.stderr)
            self.tsdb = None
