"""Kafka-client transport: the broker API against a real Kafka cluster.

The in-process broker + TCP protocol is the CI/serving path; THIS
adapter implements the same five-method broker surface
(create_topic / topics / produce / fetch / end_offset, plus sync) over
aiokafka, so where a real Kafka broker exists the reference's own
clients — kafkajs in topic.js:8, exchange_test.js:6-12, consumer.js:6-13
— connect to the SAME topics the engine serves, and the unmodified Node
harness drives the engine end-to-end:

    kafka-server-start ...                      # real broker :9092
    node topic.js                               # or: kme-provision
    python -m kme_tpu.bridge.serve --kafka localhost:9092 &
    node exchange_test.js ; node consumer.js    # unmodified harness

aiokafka is an OPTIONAL dependency: importing this module works without
it; constructing KafkaBroker raises a clear error when absent. The
adapter's own logic (offset bookkeeping, key/value codecs, partition-0
pinning, blocking-fetch semantics) is pinned by contract tests against
a faked aiokafka (tests/test_kafka_adapter.py) so the CI path never
needs a broker.

Single-partition topics, like the reference (topic.js:18,22): the
engine's ordering contract is the partition order of partition 0.
"""

from __future__ import annotations

import asyncio
from typing import Dict, List, Optional

from kme_tpu.bridge.broker import BrokerError, Record


def _aiokafka():
    try:
        import aiokafka
        import aiokafka.admin
    except ImportError as e:  # pragma: no cover - env-dependent
        raise BrokerError(
            "the Kafka transport needs the optional aiokafka package "
            "(pip install aiokafka); the in-process broker + TCP bridge "
            "needs no external dependencies") from e
    return aiokafka


class KafkaBroker:
    """Broker-API adapter over aiokafka (sync facade; a private event
    loop runs the async client calls)."""

    def __init__(self, bootstrap: str = "localhost:9092") -> None:
        self._k = _aiokafka()
        self.bootstrap = bootstrap
        self._loop = asyncio.new_event_loop()
        self._producer = None
        self._admin = None
        self._consumers: Dict[str, object] = {}
        self._positions: Dict[str, int] = {}

    # ------------------------------------------------------------------
    def _run(self, coro):
        return self._loop.run_until_complete(coro)

    def _make(self, factory):
        """Construct a client INSIDE the private loop: aiokafka >= 0.8
        dropped the loop= kwarg and resolves the running loop itself."""
        async def mk():
            return factory()

        return self._run(mk())

    def _get_producer(self):
        if self._producer is None:
            p = self._make(lambda: self._k.AIOKafkaProducer(
                bootstrap_servers=self.bootstrap))
            self._run(p.start())
            self._producer = p
        return self._producer

    def _get_consumer(self, topic: str):
        c = self._consumers.get(topic)
        if c is None:
            c = self._make(lambda: self._k.AIOKafkaConsumer(
                bootstrap_servers=self.bootstrap,
                enable_auto_commit=False, auto_offset_reset="earliest"))
            self._run(c.start())
            tp = self._k.TopicPartition(topic, 0)
            c.assign([tp])
            self._consumers[topic] = c
            self._positions[topic] = -1
        return c

    def _tp(self, topic: str):
        return self._k.TopicPartition(topic, 0)

    # -------------------------------------------------- broker surface
    def _get_admin(self):
        if self._admin is None:
            a = self._make(lambda: self._k.admin.AIOKafkaAdminClient(
                bootstrap_servers=self.bootstrap))
            self._run(a.start())
            self._admin = a
        return self._admin

    def create_topic(self, name: str, partitions: int = 1) -> bool:
        """kafkajs admin.createTopics semantics (topic.js:14-25):
        False when the topic already exists."""
        admin = self._get_admin()
        existing = self._run(admin.list_topics())
        if name in existing:
            return False
        new = self._k.admin.NewTopic(
            name=name, num_partitions=partitions, replication_factor=1)
        self._run(admin.create_topics([new]))
        return True

    def topics(self) -> Dict[str, int]:
        return {t: 1 for t in self._run(self._get_admin().list_topics())
                if not t.startswith("__")}

    def produce(self, topic: str, key: Optional[str], value: str) -> int:
        p = self._get_producer()
        md = self._run(p.send_and_wait(
            topic, value.encode("utf-8"),
            key=None if key is None else key.encode("utf-8"),
            partition=0))
        return md.offset

    def fetch(self, topic: str, offset: int, max_records: int = 1024,
              timeout: float = 0.0) -> List[Record]:
        c = self._get_consumer(topic)
        tp = self._tp(topic)
        if self._positions.get(topic) != offset:
            c.seek(tp, offset)          # aiokafka's seek is synchronous
            self._positions[topic] = offset
        batches = self._run(c.getmany(
            tp, timeout_ms=max(int(timeout * 1000), 0),
            max_records=max_records))
        recs = []
        for msgs in batches.values():
            for m in msgs:
                recs.append(Record(
                    offset=m.offset,
                    key=None if m.key is None else m.key.decode("utf-8"),
                    value=m.value.decode("utf-8")))
        if recs:
            self._positions[topic] = recs[-1].offset + 1
        return recs

    def end_offset(self, topic: str) -> int:
        c = self._get_consumer(topic)
        tp = self._tp(topic)
        ends = self._run(c.end_offsets([tp]))
        return ends[tp]

    def sync(self) -> None:
        if self._producer is not None:
            self._run(self._producer.flush())

    def close(self) -> None:
        for c in self._consumers.values():
            self._run(c.stop())
        self._consumers.clear()
        if self._producer is not None:
            self._run(self._producer.stop())
            self._producer = None
        if self._admin is not None:
            self._run(self._admin.close())
            self._admin = None
