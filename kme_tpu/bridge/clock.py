"""The clock seam: every sim-reachable wait/stamp goes through here.

FoundationDB-style deterministic simulation (kme_tpu/sim/) runs the
whole cluster in one process under a virtual clock. That only works if
no component reads wall time or sleeps on the real OS behind the
scheduler's back — a single stray ``time.sleep`` turns a reproducible
interleaving into a wall-clock race. The supervisor grew an injectable
clock in PR 6; this module is the shared seam the rest of ``bridge/``
(service retry/backoff, broker admission stamps, replica follow loop,
TCP client re-stamping) threads through, so the simulator substitutes
ONE object instead of monkeypatching four modules.

Two implementations:

- ``WallClock`` — the production default; trivial delegation to
  ``time``. Module singleton ``WALL`` so hot paths share one instance.
- ``VirtualClock`` — a manually advanced clock for the simulator and
  for unit tests. ``sleep()`` never blocks: it advances virtual time
  (standalone use) or defers to an installed scheduler hook
  (cooperative use under ``kme_tpu.sim``), so a component that naps for
  backoff costs simulated milliseconds, not real ones.

kme-lint enforces the seam: functions listed in ``CLOCK_SCOPES``
(analysis/rules.py) may not call ``time.time/monotonic/sleep/time_ns``
directly — rule KME-C001 fires on any regression.
"""

from __future__ import annotations

import time as _time
from typing import Callable, Optional


class Clock:
    """Interface + production implementation contract.

    ``time()``/``time_ns()``/``time_us()`` are the wall ("admission
    stamp") domain; ``monotonic()`` is the interval domain (heartbeats,
    backoff deadlines); ``sleep()`` is the only blocking primitive.
    """

    def time(self) -> float:
        raise NotImplementedError

    def time_ns(self) -> int:
        raise NotImplementedError

    def time_us(self) -> int:
        """Microsecond admission stamps (broker ``ats``)."""
        return self.time_ns() // 1000

    def monotonic(self) -> float:
        raise NotImplementedError

    def sleep(self, seconds: float) -> None:
        raise NotImplementedError


class WallClock(Clock):
    """The real thing (production default)."""

    def time(self) -> float:
        return _time.time()

    def time_ns(self) -> int:
        return _time.time_ns()

    def monotonic(self) -> float:
        return _time.monotonic()

    def sleep(self, seconds: float) -> None:
        if seconds > 0:
            _time.sleep(seconds)


#: Shared production instance — ``clock or WALL`` is the idiom at every
#: seam, so None-configured components never allocate.
WALL = WallClock()


class VirtualClock(Clock):
    """A deterministic clock that only moves when told to.

    Standalone (no hook): ``sleep(s)`` advances ``now`` by ``s`` — unit
    tests of backoff logic complete instantly. Under the simulator a
    ``sleep_hook`` is installed and owns the advance: the cooperative
    scheduler charges the sleeping actor virtual time without blocking
    the process.

    ``skew``: per-actor wall offset (the ``clock.skew`` fault point) —
    shifts ``time()``-domain reads only, never ``monotonic()``, exactly
    like a stepped NTP adjustment on a real host.
    """

    def __init__(self, start: float = 0.0,
                 sleep_hook: Optional[Callable[[float], None]] = None
                 ) -> None:
        self.now = float(start)
        self.skew = 0.0
        self.sleep_hook = sleep_hook
        self.slept_total = 0.0      # telemetry: virtual seconds napped

    def time(self) -> float:
        return self.now + self.skew

    def time_ns(self) -> int:
        return int((self.now + self.skew) * 1e9)

    def monotonic(self) -> float:
        return self.now

    def advance(self, seconds: float) -> None:
        if seconds > 0:
            self.now += seconds

    def sleep(self, seconds: float) -> None:
        if seconds <= 0:
            return
        self.slept_total += seconds
        if self.sleep_hook is not None:
            self.sleep_hook(seconds)
        else:
            self.now += seconds
