"""Transport bridge: the L0/L4 edge of the framework.

The reference's transport is an external Kafka broker with two topics
(`MatchIn`, `MatchOut`, one partition each — /root/reference/topic.js:14-25)
between the Node harness and the Streams engine. Here the same contract
is a small native-Python stack:

- broker.py   — the broker core: named topics, single-partition ordered
                logs, offset-based fetch (the semantics the reference
                relies on: 1 partition => total order).
- tcp.py      — the process boundary: a JSON-lines TCP server/client pair
                exposing the broker API on a socket, so the provisioner,
                load generator, engine service and consumer run as
                separate OS processes like the reference's stack.
- service.py  — the engine service: polls MatchIn, runs a configurable
                engine (device lanes engine or scalar oracle replica),
                forwards the IN/OUT record stream to MatchOut
                (KProcessor.java:97, 124).
- provision.py/serve.py/consume.py — the CLI roles (topic.js /
                KProcessor.main / consumer.js).
"""

from kme_tpu.bridge.broker import BrokerError, InProcessBroker, Record

__all__ = ["BrokerError", "InProcessBroker", "Record"]
