"""One-time JAX configuration for the device-side modules.

int64 is part of the engine's data model (Java `long` balances/ids,
KProcessor.java:30-33, 451-455). JAX downcasts to int32 unless x64 is
enabled; device modules import this module before touching jax.numpy.
The hot matching path still uses explicit int32 arrays — only ledger
arithmetic is 64-bit. Pure-Python layers (wire/oracle/workload) do not
import this, so they stay usable without JAX.
"""

import jax

jax.config.update("jax_enable_x64", True)
