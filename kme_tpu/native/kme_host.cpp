// Native host-runtime core: the conflict-free scheduler hot loop.
//
// The reference's runtime substrate is native third-party code behind the
// JVM (RocksDB JNI, Kafka clients — SURVEY.md §2.4); here the host
// runtime's hot loop — planning wire messages into conflict-free
// (segment, step, lane, slot) coordinates (kme_tpu/runtime/sequencer.py,
// the semantics authority) — has a C++ implementation bound over a C ABI
// with ctypes. Behavior must match the Python scheduler EXACTLY
// (tests/test_native_sched.py compares full plans field by field); the
// Python implementation remains the fallback when no toolchain exists.
//
// Build: g++ -O3 -shared -fPIC kme_host.cpp -o kme_host.so
// (driven by kme_tpu/native/__init__.py, cached by source hash).

#include <algorithm>
#include <climits>
#include <cstdint>
#include <cstring>
#include <unordered_map>
#include <vector>

namespace {

// lane opcodes — must match kme_tpu/engine/lanes.py
constexpr int32_t L_BUY = 1, L_SELL = 2, L_CANCEL = 3, L_CREATE = 4,
                  L_TRANSFER = 5, L_ADD_SYMBOL = 6;
// wire opcodes — must match kme_tpu/opcodes.py
constexpr int64_t OP_ADD_SYMBOL = 0, OP_REMOVE_SYMBOL = 1, OP_BUY = 2,
                  OP_SELL = 3, OP_CANCEL = 4, OP_CREATE_BALANCE = 100,
                  OP_TRANSFER = 101, OP_PAYOUT = 200;

constexpr int32_t ST_OK = 0, ST_CAP_ACCOUNTS = 1, ST_CAP_SYMBOLS = 2;

struct Sched {
  int32_t S, A, width;
  std::unordered_map<int64_t, int32_t> aid_idx;
  std::unordered_map<int64_t, int32_t> sid_lane;
  std::unordered_map<int64_t, int64_t> oid_sid;
  int32_t rr_lane = 0;

  // plan outputs (valid until the next plan() call)
  std::vector<int64_t> p_msg, p_oid;
  std::vector<int32_t> p_seg, p_step, p_lane, p_act, p_aidx, p_price,
      p_size, p_slot;
  std::vector<int64_t> b_msg, b_credit;
  std::vector<int32_t> b_lane, b_mode;
  std::vector<int64_t> r_msg;              // host rejects
  std::vector<int32_t> seg_steps;
  std::vector<int32_t> program;            // (kind, idx) pairs; kind 0=scan 1=barrier
  int64_t err_value = 0;                   // offending aid/sid on capacity error
};

struct PlanState {
  Sched* s;
  std::vector<int32_t> lane_next;
  std::unordered_map<int64_t, int32_t> actor_next;
  std::unordered_map<int32_t, int32_t> step_fill;
  int32_t first_open = 0;
  int32_t seg = 0, seg_height = 0;

  explicit PlanState(Sched* sp) : s(sp), lane_next(sp->S, 0) {}

  void close_segment() {
    if (seg_height > 0) {
      s->seg_steps.push_back(seg_height);
      s->program.push_back(0);  // scan
      s->program.push_back(static_cast<int32_t>(s->seg_steps.size()) - 1);
      seg += 1;
    }
    std::fill(lane_next.begin(), lane_next.end(), 0);
    for (auto& kv : actor_next) kv.second = 0;
    step_fill.clear();
    first_open = 0;
    seg_height = 0;
  }

  void place(int64_t i, int32_t lane, int32_t lane_act, int32_t aidx,
             int64_t oid, int32_t price, int32_t size, bool has_actor,
             int64_t actor_key) {
    int32_t step = lane_next[lane];
    if (has_actor) {
      auto it = actor_next.find(actor_key);
      if (it != actor_next.end() && it->second > step) step = it->second;
    }
    int32_t slot = 0;
    if (s->width > 0) {
      if (first_open > step) step = first_open;
      for (;;) {
        auto it = step_fill.find(step);
        if (it == step_fill.end() || it->second < s->width) break;
        step += 1;
      }
      auto& cnt = step_fill[step];
      slot = cnt;
      cnt += 1;
      for (;;) {
        auto it = step_fill.find(first_open);
        if (it == step_fill.end() || it->second < s->width) break;
        first_open += 1;
      }
    }
    s->p_msg.push_back(i);
    s->p_seg.push_back(seg);
    s->p_step.push_back(step);
    s->p_lane.push_back(lane);
    s->p_act.push_back(lane_act);
    s->p_aidx.push_back(aidx);
    s->p_oid.push_back(oid);
    s->p_price.push_back(price);
    s->p_size.push_back(size);
    s->p_slot.push_back(slot);
    lane_next[lane] = step + 1;
    if (has_actor) actor_next[actor_key] = step + 1;
    if (step + 1 > seg_height) seg_height = step + 1;
  }

  int32_t free_lane(int32_t step_floor) {
    // prefer a lane whose clock is <= the actor clock (no stall),
    // probing round-robin from rr_lane; else the global argmin (first
    // index on ties — matches Python's min())
    for (int32_t probe = 0; probe < s->S; ++probe) {
      int32_t lane = (s->rr_lane + probe) % s->S;
      if (lane_next[lane] <= step_floor) {
        s->rr_lane = (lane + 1) % s->S;
        return lane;
      }
    }
    int32_t best = 0;
    for (int32_t lane = 1; lane < s->S; ++lane)
      if (lane_next[lane] < lane_next[best]) best = lane;
    s->rr_lane = (best + 1) % s->S;
    return best;
  }
};

}  // namespace

extern "C" {

Sched* kme_sched_new(int32_t lanes, int32_t accounts, int32_t width) {
  Sched* s = new Sched();
  s->S = lanes;
  s->A = accounts;
  s->width = width;
  return s;
}

void kme_sched_free(Sched* s) { delete s; }

// Returns ST_* status. Columns are int64 (price/size pre-validated to
// int32 range, oids pre-wrapped to Java-long, by the Python wrapper).
int32_t kme_sched_plan(Sched* s, int64_t n, const int64_t* action,
                       const int64_t* oid, const int64_t* aid,
                       const int64_t* sid, const int64_t* price,
                       const int64_t* size) {
  s->p_msg.clear(); s->p_seg.clear(); s->p_step.clear(); s->p_lane.clear();
  s->p_act.clear(); s->p_aidx.clear(); s->p_oid.clear(); s->p_price.clear();
  s->p_size.clear(); s->p_slot.clear();
  s->b_msg.clear(); s->b_lane.clear(); s->b_mode.clear(); s->b_credit.clear();
  s->r_msg.clear(); s->seg_steps.clear(); s->program.clear();
  s->err_value = 0;

  PlanState ps(s);

  auto acct = [&](int64_t a, int32_t* out) -> bool {
    auto it = s->aid_idx.find(a);
    if (it != s->aid_idx.end()) { *out = it->second; return true; }
    if (static_cast<int32_t>(s->aid_idx.size()) >= s->A) {
      s->err_value = a;
      return false;
    }
    int32_t idx = static_cast<int32_t>(s->aid_idx.size());
    s->aid_idx.emplace(a, idx);
    *out = idx;
    return true;
  };
  auto lane_of = [&](int64_t sym, int32_t* out) -> bool {
    auto it = s->sid_lane.find(sym);
    if (it != s->sid_lane.end()) { *out = it->second; return true; }
    if (static_cast<int32_t>(s->sid_lane.size()) >= s->S) {
      s->err_value = sym;
      return false;
    }
    int32_t lane = static_cast<int32_t>(s->sid_lane.size());
    s->sid_lane.emplace(sym, lane);
    *out = lane;
    return true;
  };

  for (int64_t i = 0; i < n; ++i) {
    const int64_t a = action[i];
    if (a == OP_BUY || a == OP_SELL) {
      int32_t lane, aidx;
      if (!lane_of(sid[i], &lane)) return ST_CAP_SYMBOLS;
      if (!acct(aid[i], &aidx)) return ST_CAP_ACCOUNTS;
      s->oid_sid[oid[i]] = sid[i];
      ps.place(i, lane, a == OP_BUY ? L_BUY : L_SELL, aidx, oid[i],
               static_cast<int32_t>(price[i]), static_cast<int32_t>(size[i]),
               true, aid[i]);
    } else if (a == OP_CANCEL) {
      auto it = s->oid_sid.find(oid[i]);
      if (it == s->oid_sid.end()) {
        s->r_msg.push_back(i);
        continue;
      }
      int32_t lane, aidx;
      if (!lane_of(it->second, &lane)) return ST_CAP_SYMBOLS;
      if (!acct(aid[i], &aidx)) return ST_CAP_ACCOUNTS;
      ps.place(i, lane, L_CANCEL, aidx, oid[i],
               static_cast<int32_t>(price[i]), static_cast<int32_t>(size[i]),
               true, aid[i]);
    } else if (a == OP_CREATE_BALANCE || a == OP_TRANSFER) {
      int32_t aidx;
      if (!acct(aid[i], &aidx)) return ST_CAP_ACCOUNTS;
      int32_t floor = 0;
      auto it = ps.actor_next.find(aid[i]);
      if (it != ps.actor_next.end()) floor = it->second;
      int32_t lane = ps.free_lane(floor);
      ps.place(i, lane, a == OP_CREATE_BALANCE ? L_CREATE : L_TRANSFER,
               aidx, oid[i], static_cast<int32_t>(price[i]),
               static_cast<int32_t>(size[i]), true, aid[i]);
    } else if (a == OP_ADD_SYMBOL) {
      if (sid[i] < 0) {
        s->r_msg.push_back(i);
        continue;
      }
      int32_t lane;
      if (!lane_of(sid[i], &lane)) return ST_CAP_SYMBOLS;
      ps.place(i, lane, L_ADD_SYMBOL, 0, oid[i],
               static_cast<int32_t>(price[i]), static_cast<int32_t>(size[i]),
               false, 0);
    } else if (a == OP_REMOVE_SYMBOL || a == OP_PAYOUT) {
      // abs(INT64_MIN) is not representable (and negating it is UB):
      // the Python authority computes 2^63, which can never match a
      // wrapped map key, so host-reject without negating
      if (sid[i] == INT64_MIN) {
        s->r_msg.push_back(i);
        continue;
      }
      int64_t sym = sid[i] < 0 ? -sid[i] : sid[i];
      auto it = s->sid_lane.find(sym);
      if (it == s->sid_lane.end()) {
        s->r_msg.push_back(i);
        continue;
      }
      ps.close_segment();
      int32_t mode = a == OP_REMOVE_SYMBOL ? 0 : (sid[i] >= 0 ? 1 : 2);
      s->b_msg.push_back(i);
      s->b_lane.push_back(it->second);
      s->b_mode.push_back(mode);
      s->b_credit.push_back(size[i]);
      s->program.push_back(1);  // barrier
      s->program.push_back(static_cast<int32_t>(s->b_msg.size()) - 1);
      // resting-oid routes die with the wipe
      for (auto oit = s->oid_sid.begin(); oit != s->oid_sid.end();) {
        if (oit->second == sym) oit = s->oid_sid.erase(oit);
        else ++oit;
      }
    } else {
      s->r_msg.push_back(i);  // unknown opcode
    }
  }
  ps.close_segment();
  return ST_OK;
}

// ---- plan output getters (pointers valid until the next plan/free) ----
int64_t kme_sched_n_placed(Sched* s) { return (int64_t)s->p_msg.size(); }
const int64_t* kme_sched_p_msg(Sched* s) { return s->p_msg.data(); }
const int32_t* kme_sched_p_seg(Sched* s) { return s->p_seg.data(); }
const int32_t* kme_sched_p_step(Sched* s) { return s->p_step.data(); }
const int32_t* kme_sched_p_lane(Sched* s) { return s->p_lane.data(); }
const int32_t* kme_sched_p_act(Sched* s) { return s->p_act.data(); }
const int32_t* kme_sched_p_aidx(Sched* s) { return s->p_aidx.data(); }
const int64_t* kme_sched_p_oid(Sched* s) { return s->p_oid.data(); }
const int32_t* kme_sched_p_price(Sched* s) { return s->p_price.data(); }
const int32_t* kme_sched_p_size(Sched* s) { return s->p_size.data(); }
const int32_t* kme_sched_p_slot(Sched* s) { return s->p_slot.data(); }
int64_t kme_sched_n_barriers(Sched* s) { return (int64_t)s->b_msg.size(); }
const int64_t* kme_sched_b_msg(Sched* s) { return s->b_msg.data(); }
const int32_t* kme_sched_b_lane(Sched* s) { return s->b_lane.data(); }
const int32_t* kme_sched_b_mode(Sched* s) { return s->b_mode.data(); }
const int64_t* kme_sched_b_credit(Sched* s) { return s->b_credit.data(); }
int64_t kme_sched_n_rejects(Sched* s) { return (int64_t)s->r_msg.size(); }
const int64_t* kme_sched_r_msg(Sched* s) { return s->r_msg.data(); }
int64_t kme_sched_n_segments(Sched* s) { return (int64_t)s->seg_steps.size(); }
const int32_t* kme_sched_seg_steps(Sched* s) { return s->seg_steps.data(); }
int64_t kme_sched_n_program(Sched* s) { return (int64_t)s->program.size() / 2; }
const int32_t* kme_sched_program(Sched* s) { return s->program.data(); }
int64_t kme_sched_err_value(Sched* s) { return s->err_value; }

// ---- id-space state (for checkpoint export/import + reconstruction) ----
int64_t kme_sched_n_accounts(Sched* s) { return (int64_t)s->aid_idx.size(); }
int64_t kme_sched_n_symbols(Sched* s) { return (int64_t)s->sid_lane.size(); }
int64_t kme_sched_n_routes(Sched* s) { return (int64_t)s->oid_sid.size(); }
int32_t kme_sched_rr_lane(Sched* s) { return s->rr_lane; }
void kme_sched_set_rr_lane(Sched* s, int32_t v) { s->rr_lane = v; }

void kme_sched_export_accounts(Sched* s, int64_t* keys, int32_t* vals) {
  int64_t i = 0;
  for (auto& kv : s->aid_idx) { keys[i] = kv.first; vals[i] = kv.second; ++i; }
}
void kme_sched_export_symbols(Sched* s, int64_t* keys, int32_t* vals) {
  int64_t i = 0;
  for (auto& kv : s->sid_lane) { keys[i] = kv.first; vals[i] = kv.second; ++i; }
}
void kme_sched_export_routes(Sched* s, int64_t* keys, int64_t* vals) {
  int64_t i = 0;
  for (auto& kv : s->oid_sid) { keys[i] = kv.first; vals[i] = kv.second; ++i; }
}
void kme_sched_import_accounts(Sched* s, int64_t n, const int64_t* keys,
                               const int32_t* vals) {
  s->aid_idx.clear();
  for (int64_t i = 0; i < n; ++i) s->aid_idx.emplace(keys[i], vals[i]);
}
void kme_sched_import_symbols(Sched* s, int64_t n, const int64_t* keys,
                              const int32_t* vals) {
  s->sid_lane.clear();
  for (int64_t i = 0; i < n; ++i) s->sid_lane.emplace(keys[i], vals[i]);
}
void kme_sched_import_routes(Sched* s, int64_t n, const int64_t* keys,
                             const int64_t* vals) {
  s->oid_sid.clear();
  for (int64_t i = 0; i < n; ++i) s->oid_sid.emplace(keys[i], vals[i]);
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Batch plan: route + H2D staging pack in one call (the plan half of the
// native host path). Calls the seq router through its own C ABI (same
// shared object) and packs the routed columns straight into the stacked
// (K, B) int32 scan-input planes, replacing SeqSession._plan's numpy
// zero-pad + int64 split. Plane order matches the scan's input dict:
//   [act, aid, price, size, lane, oid_lo, oid_hi], plane-major, K*B each.
// ---------------------------------------------------------------------------

extern "C" {
int32_t kme_router_route(void*, int64_t, const int64_t*, const int64_t*,
                         const int64_t*, const int64_t*, const int64_t*,
                         const int64_t*);
int64_t kme_router_n_routed(void*);
const int32_t* kme_router_o_act(void*);
const int32_t* kme_router_o_aidx(void*);
const int32_t* kme_router_o_price(void*);
const int32_t* kme_router_o_size(void*);
const int32_t* kme_router_o_lane(void*);
const int64_t* kme_router_o_oid(void*);
}

namespace {

// Rotating plane buffers: the Python side hands the planes to the jit
// dispatch zero-copy, and double-buffered serving keeps up to two packed
// batches in flight — four buffers give a 2x safety margin before a
// plane is overwritten.
struct Pack {
  static constexpr int NBUF = 4;
  int32_t* buf[NBUF] = {nullptr, nullptr, nullptr, nullptr};
  int64_t cap[NBUF] = {0, 0, 0, 0};
  int cur = NBUF - 1;
  int64_t err_index = -1;
  ~Pack() {
    for (int i = 0; i < NBUF; ++i) delete[] buf[i];
  }
};

}  // namespace

extern "C" {

void* kme_pack_new() { return new Pack(); }
void kme_pack_free(void* p) { delete static_cast<Pack*>(p); }

// Envelope-check + route + pack one batch. Returns K (the power-of-two
// chunk count, >= 1) on success, or:
//   -1 account-capacity exhausted   (router err_value holds the id)
//   -2 symbol-capacity exhausted
//   -3 price/size outside int32     (kme_pack_err_index holds the index;
//                                    id maps untouched, like the Python
//                                    wrapper's pre-route envelope check)
int64_t kme_plan_batch(void* pack, void* router, int64_t n,
                       const int64_t* action, const int64_t* oid,
                       const int64_t* aid, const int64_t* sid,
                       const int64_t* price, const int64_t* size,
                       int32_t B) {
  Pack& pk = *static_cast<Pack*>(pack);
  pk.err_index = -1;
  for (int64_t i = 0; i < n; ++i) {
    if (price[i] < INT32_MIN || price[i] > INT32_MAX ||
        size[i] < INT32_MIN || size[i] > INT32_MAX) {
      pk.err_index = i;
      return -3;
    }
  }
  int32_t rc = kme_router_route(router, n, action, oid, aid, sid, price,
                                size);
  if (rc != 0) return -(int64_t)rc;
  const int64_t nr = kme_router_n_routed(router);
  int64_t nk = nr > 0 ? (nr + B - 1) / B : 1;
  int64_t K = 1;
  while (K < nk) K <<= 1;
  const int64_t total = K * (int64_t)B;
  pk.cur = (pk.cur + 1) % Pack::NBUF;
  int32_t*& b = pk.buf[pk.cur];
  if (pk.cap[pk.cur] < 7 * total) {
    delete[] b;
    b = new int32_t[7 * total];
    pk.cap[pk.cur] = 7 * total;
  }
  std::memset(b, 0, sizeof(int32_t) * 7 * total);
  std::memcpy(b + 0 * total, kme_router_o_act(router), nr * 4);
  std::memcpy(b + 1 * total, kme_router_o_aidx(router), nr * 4);
  std::memcpy(b + 2 * total, kme_router_o_price(router), nr * 4);
  std::memcpy(b + 3 * total, kme_router_o_size(router), nr * 4);
  std::memcpy(b + 4 * total, kme_router_o_lane(router), nr * 4);
  const int64_t* roid = kme_router_o_oid(router);
  int32_t* lo = b + 5 * total;
  int32_t* hi = b + 6 * total;
  for (int64_t i = 0; i < nr; ++i) {
    // numpy split64 semantics: low 32 bits reinterpreted as int32,
    // high 32 via arithmetic shift then truncating cast
    lo[i] = (int32_t)(uint32_t)(uint64_t)roid[i];
    hi[i] = (int32_t)(roid[i] >> 32);
  }
  return K;
}

const int32_t* kme_pack_planes(void* p) {
  Pack& pk = *static_cast<Pack*>(p);
  return pk.buf[pk.cur];
}
int64_t kme_pack_err_index(void* p) {
  return static_cast<Pack*>(p)->err_index;
}

// Per-shard submission-queue slice (seqmesh async dispatch): gather
// one shard's rows for `n` windows out of a stacked (K, shards*bw)
// int32 plane into a dense zero-padded (kpad, bw) segment plane. One
// memcpy per window row; out-of-range window indices are skipped (the
// Python wrapper never produces them — defensive only).
void kme_shard_slice(const int32_t* src, int64_t K, int64_t shards,
                     int64_t bw, int64_t shard, const int64_t* win_idx,
                     int64_t n, int64_t kpad, int32_t* dst) {
  if (kpad > 0)
    std::memset(dst, 0, sizeof(int32_t) * (size_t)(kpad * bw));
  for (int64_t i = 0; i < n && i < kpad; ++i) {
    const int64_t w = win_idx[i];
    if (w < 0 || w >= K) continue;
    std::memcpy(dst + i * bw, src + (w * shards + shard) * bw,
                sizeof(int32_t) * (size_t)bw);
  }
}

}  // extern "C"
