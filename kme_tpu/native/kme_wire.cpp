// Native wire-stream reconstruction for the sequential engine.
//
// The engine returns compact per-message arrays + a packed fill log;
// turning those into the byte-exact `IN {...}` / `OUT {...}` record
// stream (consumer.js:19 format; Jackson template wire.order_json) was
// a per-fill Python loop costing ~1s per 100k messages — the host-side
// cap SURVEY.md §7 H5 warns about. This is the same reconstruction in
// C++ behind a C ABI: one call emits every line into a single buffer
// with per-line offsets; Python slices lazily or streams the buffer.
// Semantics authority: SeqSession.process_wire (runtime/seqsession.py);
// equivalence is pinned by tests/test_seq_engine.py.
//
// Built together with kme_host.cpp / kme_oracle.cpp by
// kme_tpu/native/__init__.py.

#include <charconv>
#include <cstdint>
#include <cstring>

namespace {

constexpr int32_t L_BUY = 1, L_SELL = 2;
constexpr int64_t OP_BOUGHT = 5, OP_SOLD = 6, OP_REJECT = 7;

struct Recon {
  // output storage (valid until the next call / free)
  char* buf = nullptr;
  int64_t cap = 0, len = 0;
  int64_t* line_off = nullptr;   // start offset of each line
  int64_t n_lines = 0, lines_cap = 0;
  int32_t* msg_lines = nullptr;  // lines per message
  int64_t nmsg_cap = 0;
  ~Recon() {
    delete[] buf;
    delete[] line_off;
    delete[] msg_lines;
  }
};

inline void put_raw(Recon& r, const char* s, int64_t n) {
  std::memcpy(r.buf + r.len, s, n);
  r.len += n;
}

inline void put_i64(Recon& r, int64_t v) {
  auto res = std::to_chars(r.buf + r.len, r.buf + r.cap, v);
  r.len = res.ptr - r.buf;
}

// order_json (wire.py): compact Jackson template, declaration order.
inline void put_order(Recon& r, int64_t action, int64_t oid, int64_t aid,
                      int64_t sid, int64_t price, int64_t size,
                      bool has_next, int64_t next, bool has_prev,
                      int64_t prev) {
  put_raw(r, "{\"action\":", 10);
  put_i64(r, action);
  put_raw(r, ",\"oid\":", 7);
  put_i64(r, oid);
  put_raw(r, ",\"aid\":", 7);
  put_i64(r, aid);
  put_raw(r, ",\"sid\":", 7);
  put_i64(r, sid);
  put_raw(r, ",\"price\":", 9);
  put_i64(r, price);
  put_raw(r, ",\"size\":", 8);
  put_i64(r, size);
  put_raw(r, ",\"next\":", 8);
  if (has_next) put_i64(r, next); else put_raw(r, "null", 4);
  put_raw(r, ",\"prev\":", 8);
  if (has_prev) put_i64(r, prev); else put_raw(r, "null", 4);
  put_raw(r, "}", 1);
}

inline void start_line(Recon& r, const char* key, int64_t klen) {
  r.line_off[r.n_lines++] = r.len;
  put_raw(r, key, klen);
}

}  // namespace

extern "C" {

void* kme_recon_new() { return new Recon(); }
void kme_recon_free(void* p) { delete static_cast<Recon*>(p); }

const char* kme_recon_buf(void* p) { return static_cast<Recon*>(p)->buf; }
int64_t kme_recon_len(void* p) { return static_cast<Recon*>(p)->len; }
int64_t kme_recon_n_lines(void* p) {
  return static_cast<Recon*>(p)->n_lines;
}
const int64_t* kme_recon_line_off(void* p) {
  return static_cast<Recon*>(p)->line_off;
}
const int32_t* kme_recon_msg_lines(void* p) {
  return static_cast<Recon*>(p)->msg_lines;
}

// Returns 0 on success. All per-message arrays are in arrival order.
// d_* arrays are valid where d_isdev != 0; trades carry d_sid (the
// lane's symbol) and their fills live at f_*[d_off .. d_off+d_nfill).
int32_t kme_recon_wire(
    int64_t nmsg, const int64_t* m_action, const int64_t* m_oid,
    const int64_t* m_aid, const int64_t* m_sid, const int64_t* m_price,
    const int64_t* m_size, const int64_t* m_next, const uint8_t* m_has_next,
    const int64_t* m_prev, const uint8_t* m_has_prev,
    const uint8_t* d_isdev, const int32_t* d_act, const uint8_t* d_ok,
    const int32_t* d_nfill, const int64_t* d_off, const int64_t* d_residual,
    const int64_t* d_prev_oid, const uint8_t* d_append, const int64_t* d_sid,
    int64_t nfills, const int64_t* f_oid, const int64_t* f_aid,
    const int64_t* f_price, const int64_t* f_size, void* handle) {
  Recon& r = *static_cast<Recon*>(handle);
  // worst-case line budget: IN + OUT per message + 2 lines per fill.
  // Longest line: "OUT " (4) + 65 bytes of JSON scaffolding + 8 fields
  // of up to 20 chars (int64 min) = 229; 240 leaves slack.
  int64_t lines = 2 * nmsg + 2 * nfills;
  int64_t need = 240 * lines + 64;
  if (r.cap < need) {
    delete[] r.buf;
    r.buf = new char[need];
    r.cap = need;
  }
  if (r.lines_cap < lines) {
    delete[] r.line_off;
    r.line_off = new int64_t[lines];
    r.lines_cap = lines;
  }
  if (r.nmsg_cap < nmsg) {
    delete[] r.msg_lines;
    r.msg_lines = new int32_t[nmsg];
    r.nmsg_cap = nmsg;
  }
  r.len = 0;
  r.n_lines = 0;

  for (int64_t i = 0; i < nmsg; i++) {
    int64_t lines0 = r.n_lines;
    start_line(r, "IN ", 3);
    put_order(r, m_action[i], m_oid[i], m_aid[i], m_sid[i], m_price[i],
              m_size[i], m_has_next[i], m_next[i], m_has_prev[i],
              m_prev[i]);
    bool isdev = d_isdev[i] != 0;
    bool ok = isdev && d_ok[i] != 0;
    if (!ok) {
      start_line(r, "OUT ", 4);
      put_order(r, OP_REJECT, m_oid[i], m_aid[i], m_sid[i], m_price[i],
                m_size[i], m_has_next[i], m_next[i], m_has_prev[i],
                m_prev[i]);
    } else {
      int32_t act = d_act[i];
      bool is_trade = act == L_BUY || act == L_SELL;
      if (is_trade) {
        int64_t sid = d_sid[i];
        int64_t mk = act == L_BUY ? OP_SOLD : OP_BOUGHT;
        int64_t tk = act == L_BUY ? OP_BOUGHT : OP_SOLD;
        int64_t o0 = d_off[i];
        for (int32_t e = 0; e < d_nfill[i]; e++) {
          start_line(r, "OUT ", 4);
          put_order(r, mk, f_oid[o0 + e], f_aid[o0 + e], sid, 0,
                    f_size[o0 + e], false, 0, false, 0);
          start_line(r, "OUT ", 4);
          put_order(r, tk, m_oid[i], m_aid[i], sid,
                    m_price[i] - f_price[o0 + e], f_size[o0 + e],
                    false, 0, false, 0);
        }
        start_line(r, "OUT ", 4);
        bool app = d_append[i] != 0;
        put_order(r, m_action[i], m_oid[i], m_aid[i], m_sid[i],
                  m_price[i], d_residual[i], m_has_next[i], m_next[i],
                  app || m_has_prev[i], app ? d_prev_oid[i] : m_prev[i]);
      } else {
        start_line(r, "OUT ", 4);
        put_order(r, m_action[i], m_oid[i], m_aid[i], m_sid[i],
                  m_price[i], m_size[i], m_has_next[i], m_next[i],
                  m_has_prev[i], m_prev[i]);
      }
    }
    r.msg_lines[i] = static_cast<int32_t>(r.n_lines - lines0);
  }
  return 0;
}

}  // extern "C"
