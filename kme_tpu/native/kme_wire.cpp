// Native wire-stream reconstruction for the sequential engine.
//
// The engine returns compact per-message arrays + a packed fill log;
// turning those into the byte-exact `IN {...}` / `OUT {...}` record
// stream (consumer.js:19 format; Jackson template wire.order_json) was
// a per-fill Python loop costing ~1s per 100k messages — the host-side
// cap SURVEY.md §7 H5 warns about. This is the same reconstruction in
// C++ behind a C ABI: one call emits every line into a single buffer
// with per-line offsets; Python slices lazily or streams the buffer.
// Semantics authority: SeqSession.process_wire (runtime/seqsession.py);
// equivalence is pinned by tests/test_seq_engine.py.
//
// Built together with kme_host.cpp / kme_oracle.cpp by
// kme_tpu/native/__init__.py.

#include <charconv>
#include <cstdint>
#include <cstring>

namespace {

constexpr int32_t L_BUY = 1, L_SELL = 2;
constexpr int64_t OP_BOUGHT = 5, OP_SOLD = 6, OP_REJECT = 7;

struct Recon {
  // output storage (valid until the next call / free)
  char* buf = nullptr;
  int64_t cap = 0, len = 0;
  int64_t* line_off = nullptr;   // start offset of each line
  int64_t n_lines = 0, lines_cap = 0;
  int32_t* msg_lines = nullptr;  // lines per message
  int64_t nmsg_cap = 0;
  ~Recon() {
    delete[] buf;
    delete[] line_off;
    delete[] msg_lines;
  }
};

inline void put_raw(Recon& r, const char* s, int64_t n) {
  std::memcpy(r.buf + r.len, s, n);
  r.len += n;
}

inline void put_i64(Recon& r, int64_t v) {
  auto res = std::to_chars(r.buf + r.len, r.buf + r.cap, v);
  r.len = res.ptr - r.buf;
}

// order_json (wire.py): compact Jackson template, declaration order.
inline void put_order(Recon& r, int64_t action, int64_t oid, int64_t aid,
                      int64_t sid, int64_t price, int64_t size,
                      bool has_next, int64_t next, bool has_prev,
                      int64_t prev) {
  put_raw(r, "{\"action\":", 10);
  put_i64(r, action);
  put_raw(r, ",\"oid\":", 7);
  put_i64(r, oid);
  put_raw(r, ",\"aid\":", 7);
  put_i64(r, aid);
  put_raw(r, ",\"sid\":", 7);
  put_i64(r, sid);
  put_raw(r, ",\"price\":", 9);
  put_i64(r, price);
  put_raw(r, ",\"size\":", 8);
  put_i64(r, size);
  put_raw(r, ",\"next\":", 8);
  if (has_next) put_i64(r, next); else put_raw(r, "null", 4);
  put_raw(r, ",\"prev\":", 8);
  if (has_prev) put_i64(r, prev); else put_raw(r, "null", 4);
  put_raw(r, "}", 1);
}

inline void start_line(Recon& r, const char* key, int64_t klen) {
  r.line_off[r.n_lines++] = r.len;
  put_raw(r, key, klen);
}

}  // namespace

extern "C" {

void* kme_recon_new() { return new Recon(); }
void kme_recon_free(void* p) { delete static_cast<Recon*>(p); }

const char* kme_recon_buf(void* p) { return static_cast<Recon*>(p)->buf; }
int64_t kme_recon_len(void* p) { return static_cast<Recon*>(p)->len; }
int64_t kme_recon_n_lines(void* p) {
  return static_cast<Recon*>(p)->n_lines;
}
const int64_t* kme_recon_line_off(void* p) {
  return static_cast<Recon*>(p)->line_off;
}
const int32_t* kme_recon_msg_lines(void* p) {
  return static_cast<Recon*>(p)->msg_lines;
}

// Returns 0 on success. All per-message arrays are in arrival order.
// d_* arrays are valid where d_isdev != 0; trades carry d_sid (the
// lane's symbol) and their fills live at f_*[d_off .. d_off+d_nfill).
int32_t kme_recon_wire(
    int64_t nmsg, const int64_t* m_action, const int64_t* m_oid,
    const int64_t* m_aid, const int64_t* m_sid, const int64_t* m_price,
    const int64_t* m_size, const int64_t* m_next, const uint8_t* m_has_next,
    const int64_t* m_prev, const uint8_t* m_has_prev,
    const uint8_t* d_isdev, const int32_t* d_act, const uint8_t* d_ok,
    const int32_t* d_nfill, const int64_t* d_off, const int64_t* d_residual,
    const int64_t* d_prev_oid, const uint8_t* d_append, const int64_t* d_sid,
    int64_t nfills, const int64_t* f_oid, const int64_t* f_aid,
    const int64_t* f_price, const int64_t* f_size, void* handle) {
  Recon& r = *static_cast<Recon*>(handle);
  // worst-case line budget: IN + OUT per message + 2 lines per fill.
  // Longest line: "OUT " (4) + 65 bytes of JSON scaffolding + 8 fields
  // of up to 20 chars (int64 min) = 229; 240 leaves slack.
  int64_t lines = 2 * nmsg + 2 * nfills;
  int64_t need = 240 * lines + 64;
  if (r.cap < need) {
    delete[] r.buf;
    r.buf = new char[need];
    r.cap = need;
  }
  if (r.lines_cap < lines) {
    delete[] r.line_off;
    r.line_off = new int64_t[lines];
    r.lines_cap = lines;
  }
  if (r.nmsg_cap < nmsg) {
    delete[] r.msg_lines;
    r.msg_lines = new int32_t[nmsg];
    r.nmsg_cap = nmsg;
  }
  r.len = 0;
  r.n_lines = 0;

  for (int64_t i = 0; i < nmsg; i++) {
    int64_t lines0 = r.n_lines;
    start_line(r, "IN ", 3);
    put_order(r, m_action[i], m_oid[i], m_aid[i], m_sid[i], m_price[i],
              m_size[i], m_has_next[i], m_next[i], m_has_prev[i],
              m_prev[i]);
    bool isdev = d_isdev[i] != 0;
    bool ok = isdev && d_ok[i] != 0;
    if (!ok) {
      start_line(r, "OUT ", 4);
      put_order(r, OP_REJECT, m_oid[i], m_aid[i], m_sid[i], m_price[i],
                m_size[i], m_has_next[i], m_next[i], m_has_prev[i],
                m_prev[i]);
    } else {
      int32_t act = d_act[i];
      bool is_trade = act == L_BUY || act == L_SELL;
      if (is_trade) {
        int64_t sid = d_sid[i];
        int64_t mk = act == L_BUY ? OP_SOLD : OP_BOUGHT;
        int64_t tk = act == L_BUY ? OP_BOUGHT : OP_SOLD;
        int64_t o0 = d_off[i];
        for (int32_t e = 0; e < d_nfill[i]; e++) {
          start_line(r, "OUT ", 4);
          put_order(r, mk, f_oid[o0 + e], f_aid[o0 + e], sid, 0,
                    f_size[o0 + e], false, 0, false, 0);
          start_line(r, "OUT ", 4);
          put_order(r, tk, m_oid[i], m_aid[i], sid,
                    m_price[i] - f_price[o0 + e], f_size[o0 + e],
                    false, 0, false, 0);
        }
        start_line(r, "OUT ", 4);
        bool app = d_append[i] != 0;
        put_order(r, m_action[i], m_oid[i], m_aid[i], m_sid[i],
                  m_price[i], d_residual[i], m_has_next[i], m_next[i],
                  app || m_has_prev[i], app ? d_prev_oid[i] : m_prev[i]);
      } else {
        start_line(r, "OUT ", 4);
        put_order(r, m_action[i], m_oid[i], m_aid[i], m_sid[i],
                  m_price[i], m_size[i], m_has_next[i], m_next[i],
                  m_has_prev[i], m_prev[i]);
      }
    }
    r.msg_lines[i] = static_cast<int32_t>(r.n_lines - lines0);
  }
  return 0;
}

// One-pass reconstruction straight from the engine's routed/host arrays
// (the D2H half of the native host path). kme_recon_wire needs ~10
// per-message scatter arrays built in numpy first; this entry absorbs
// that: routed rows arrive in ascending msg-index order (the router
// emits at most one row per message, in order), so a single merge walk
// recovers isdev/act/ok/fill-window per message, translates lane -> sid
// and fill account-index -> aid through the two LUTs, and emits through
// the same line builders. Fill windows are the running sum of h_nfill
// over ALL routed rows (failed rows carry nfill 0), matching the numpy
// cumsum. Returns 0 on success, 1 on an out-of-range lane / account
// index / fill offset (the Python caller raises; numpy would IndexError
// on the same input).
int32_t kme_recon_batch(
    int64_t nmsg, const int64_t* m_action, const int64_t* m_oid,
    const int64_t* m_aid, const int64_t* m_sid, const int64_t* m_price,
    const int64_t* m_size, const int64_t* m_next, const uint8_t* m_has_next,
    const int64_t* m_prev, const uint8_t* m_has_prev,
    int64_t nr, const int64_t* r_msg, const int32_t* r_act,
    const int32_t* r_lane,
    const uint8_t* h_ok, const int64_t* h_nfill, const int64_t* h_resid,
    const int64_t* h_prev, const uint8_t* h_append,
    int64_t nlanes, const int64_t* lane_sid,
    int64_t nacct, const int64_t* idx2aid,
    int64_t nfills, const int64_t* f_oid, const int64_t* f_aidx,
    const int64_t* f_price, const int64_t* f_size, void* handle) {
  Recon& r = *static_cast<Recon*>(handle);
  int64_t lines = 2 * nmsg + 2 * nfills;
  int64_t need = 240 * lines + 64;
  if (r.cap < need) {
    delete[] r.buf;
    r.buf = new char[need];
    r.cap = need;
  }
  if (r.lines_cap < lines) {
    delete[] r.line_off;
    r.line_off = new int64_t[lines];
    r.lines_cap = lines;
  }
  if (r.nmsg_cap < nmsg) {
    delete[] r.msg_lines;
    r.msg_lines = new int32_t[nmsg];
    r.nmsg_cap = nmsg;
  }
  r.len = 0;
  r.n_lines = 0;

  int64_t k = 0;   // routed-row cursor
  int64_t o0 = 0;  // running fill offset
  for (int64_t i = 0; i < nmsg; i++) {
    int64_t lines0 = r.n_lines;
    start_line(r, "IN ", 3);
    put_order(r, m_action[i], m_oid[i], m_aid[i], m_sid[i], m_price[i],
              m_size[i], m_has_next[i], m_next[i], m_has_prev[i],
              m_prev[i]);
    bool isdev = k < nr && r_msg[k] == i;
    bool ok = isdev && h_ok[k] != 0;
    if (!ok) {
      start_line(r, "OUT ", 4);
      put_order(r, OP_REJECT, m_oid[i], m_aid[i], m_sid[i], m_price[i],
                m_size[i], m_has_next[i], m_next[i], m_has_prev[i],
                m_prev[i]);
    } else {
      int32_t act = r_act[k];
      if (act == L_BUY || act == L_SELL) {
        if (r_lane[k] < 0 || r_lane[k] >= nlanes) return 1;
        int64_t sid = lane_sid[r_lane[k]];
        int64_t mk = act == L_BUY ? OP_SOLD : OP_BOUGHT;
        int64_t tk = act == L_BUY ? OP_BOUGHT : OP_SOLD;
        for (int64_t e = 0; e < h_nfill[k]; e++) {
          if (o0 + e >= nfills) return 1;
          int64_t ai = f_aidx[o0 + e];
          if (ai < 0 || ai >= nacct) return 1;
          start_line(r, "OUT ", 4);
          put_order(r, mk, f_oid[o0 + e], idx2aid[ai], sid, 0,
                    f_size[o0 + e], false, 0, false, 0);
          start_line(r, "OUT ", 4);
          put_order(r, tk, m_oid[i], m_aid[i], sid,
                    m_price[i] - f_price[o0 + e], f_size[o0 + e],
                    false, 0, false, 0);
        }
        start_line(r, "OUT ", 4);
        bool app = h_append[k] != 0;
        put_order(r, m_action[i], m_oid[i], m_aid[i], m_sid[i],
                  m_price[i], h_resid[k], m_has_next[i], m_next[i],
                  app || m_has_prev[i], app ? h_prev[k] : m_prev[i]);
      } else {
        start_line(r, "OUT ", 4);
        put_order(r, m_action[i], m_oid[i], m_aid[i], m_sid[i],
                  m_price[i], m_size[i], m_has_next[i], m_next[i],
                  m_has_prev[i], m_prev[i]);
      }
    }
    if (isdev) {
      o0 += h_nfill[k];
      k++;
    }
    r.msg_lines[i] = static_cast<int32_t>(r.n_lines - lines0);
  }
  return 0;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// kme_parse: newline-separated JSON order messages -> columnar arrays.
//
// The input half of the wire boundary (the reference consumes JSON
// bytes from Kafka and Jackson-binds them onto the Order POJO,
// KProcessor.java:96, 448-475). Semantics authority: wire.parse_order —
// creator-bound value fields default to 0 when absent/null, next/prev
// bind by name (null/absent -> has=0), unknown keys are ignored, fields
// may appear in any order, last occurrence wins. This parser handles
// the integer/null/object subset exactly; ANY construct outside it
// (floats, strings, nested values, syntax errors, ints beyond int64)
// returns -(line+1) and the caller re-parses the whole buffer through
// the Python authority so error behavior and coercions stay identical
// (wire.WireBatch.parse_buffer).

namespace {

struct Parse {
  int64_t* cols[8] = {};  // action oid aid sid price size next prev
  uint8_t* hnext = nullptr;
  uint8_t* hprev = nullptr;
  int64_t* tidcol = nullptr;  // transport-advisory trace word (FLAG_TID)
  uint8_t* htid = nullptr;
  int64_t cap = 0, n = 0;
  int64_t err_off = 0;       // byte offset of the frame that failed
  Recon emit;                // canonical-JSON emission scratch
  int64_t* emit_off = nullptr;  // n+1 line offsets into emit.buf
  int64_t emit_off_cap = 0;
  ~Parse() {
    for (auto* c : cols) delete[] c;
    delete[] hnext;
    delete[] hprev;
    delete[] tidcol;
    delete[] htid;
    delete[] emit_off;
  }
};

inline void parse_reserve(Parse& P, int64_t n) {
  if (P.cap >= n) return;
  for (auto*& c : P.cols) {
    delete[] c;
    c = new int64_t[n];
  }
  delete[] P.hnext;
  delete[] P.hprev;
  delete[] P.tidcol;
  delete[] P.htid;
  P.hnext = new uint8_t[n];
  P.hprev = new uint8_t[n];
  P.tidcol = new int64_t[n];
  P.htid = new uint8_t[n];
  P.cap = n;
}

inline void skip_ws(const char*& p, const char* end) {
  while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) p++;
}

// parse an int64 with JSON number syntax restricted to integers:
// -?(0|[1-9][0-9]*). Returns false on anything else (incl. overflow).
inline bool parse_int(const char*& p, const char* end, int64_t* out) {
  bool neg = false;
  if (p < end && *p == '-') {
    neg = true;
    p++;
  }
  if (p >= end || *p < '0' || *p > '9') return false;
  if (*p == '0' && p + 1 < end && p[1] >= '0' && p[1] <= '9')
    return false;  // leading zero: invalid JSON
  uint64_t v = 0;
  const uint64_t lim = neg ? (uint64_t)1 << 63 : ((uint64_t)1 << 63) - 1;
  while (p < end && *p >= '0' && *p <= '9') {
    uint64_t d = (uint64_t)(*p - '0');
    if (v > (lim - d) / 10) return false;  // beyond int64
    v = v * 10 + d;
    p++;
  }
  if (p < end && (*p == '.' || *p == 'e' || *p == 'E')) return false;
  *out = neg ? (int64_t)(0 - v) : (int64_t)v;
  return true;
}

// Template fast path: the overwhelmingly common case is the exact
// Jackson template order_json emits (compact, declaration field order,
// next/prev always present). One memcmp per literal + digit runs; any
// deviation falls through to the general object walk above.
inline bool fast_line(const char* p, const char* end, int64_t* v,
                      uint8_t* has) {
  static const struct { const char* lit; int n; } L[8] = {
      {"{\"action\":", 10}, {",\"oid\":", 7}, {",\"aid\":", 7},
      {",\"sid\":", 7},     {",\"price\":", 9}, {",\"size\":", 8},
      {",\"next\":", 8},    {",\"prev\":", 8}};
  for (int f = 0; f < 8; f++) {
    if (end - p < L[f].n || std::memcmp(p, L[f].lit, L[f].n))
      return false;
    p += L[f].n;
    if (f >= 6 && end - p >= 4 && !std::memcmp(p, "null", 4)) {
      p += 4;
      v[f] = 0;
      has[f] = 0;
    } else {
      if (!parse_int(p, end, &v[f])) return false;
      has[f] = 1;
    }
  }
  return p < end && *p == '}' && p + 1 == end;
}

}  // namespace

extern "C" {

void* kme_parse_new() { return new Parse(); }
void kme_parse_free(void* p) { delete static_cast<Parse*>(p); }

const int64_t* kme_parse_col(void* p, int32_t i) {
  return static_cast<Parse*>(p)->cols[i];
}
const uint8_t* kme_parse_hnext(void* p) {
  return static_cast<Parse*>(p)->hnext;
}
const uint8_t* kme_parse_hprev(void* p) {
  return static_cast<Parse*>(p)->hprev;
}
const int64_t* kme_parse_tid(void* p) {
  return static_cast<Parse*>(p)->tidcol;
}
const uint8_t* kme_parse_htid(void* p) {
  return static_cast<Parse*>(p)->htid;
}

// Parse `len` bytes of newline-separated order JSON. Returns the line
// count on success, -(line+1) on the first line outside the fast
// subset (caller falls back to the Python authority).
int64_t kme_parse_lines(void* handle, const char* buf, int64_t len) {
  Parse& P = *static_cast<Parse*>(handle);
  // count lines (a trailing newline does not open an empty last line)
  int64_t nlines = 0;
  for (int64_t i = 0; i < len; i++)
    if (buf[i] == '\n') nlines++;
  if (len > 0 && buf[len - 1] != '\n') nlines++;
  parse_reserve(P, nlines);
  P.n = 0;
  const char* p = buf;
  const char* bend = buf + len;
  for (int64_t li = 0; li < nlines; li++) {
    const char* end = static_cast<const char*>(
        std::memchr(p, '\n', bend - p));
    if (!end) end = bend;
    int64_t v[8] = {0, 0, 0, 0, 0, 0, 0, 0};
    uint8_t has[8] = {0, 0, 0, 0, 0, 0, 0, 0};
    if (fast_line(p, end, v, has)) {
      for (int f = 0; f < 8; f++) P.cols[f][li] = v[f];
      P.hnext[li] = has[6];
      P.hprev[li] = has[7];
      P.tidcol[li] = 0;
      P.htid[li] = 0;
      P.n++;
      p = end < bend ? end + 1 : end;
      continue;
    }
    for (int f = 0; f < 8; f++) {
      v[f] = 0;
      has[f] = 0;
    }
    skip_ws(p, end);
    if (p >= end || *p != '{') return -(li + 1);
    p++;
    skip_ws(p, end);
    bool first = true;
    while (true) {
      if (p < end && *p == '}') {
        p++;
        break;
      }
      if (!first) {
        if (p >= end || *p != ',') return -(li + 1);
        p++;
        skip_ws(p, end);
      }
      first = false;
      if (p >= end || *p != '"') return -(li + 1);
      p++;
      const char* k0 = p;
      while (p < end && *p != '"') {
        if (*p == '\\') return -(li + 1);  // escaped keys: fall back
        p++;
      }
      if (p >= end) return -(li + 1);
      int64_t klen = p - k0;
      p++;
      skip_ws(p, end);
      if (p >= end || *p != ':') return -(li + 1);
      p++;
      skip_ws(p, end);
      int fi = -1;
      switch (klen) {
        case 3:
          if (!std::memcmp(k0, "oid", 3)) fi = 1;
          else if (!std::memcmp(k0, "aid", 3)) fi = 2;
          else if (!std::memcmp(k0, "sid", 3)) fi = 3;
          break;
        case 4:
          if (!std::memcmp(k0, "size", 4)) fi = 5;
          else if (!std::memcmp(k0, "next", 4)) fi = 6;
          else if (!std::memcmp(k0, "prev", 4)) fi = 7;
          break;
        case 5:
          if (!std::memcmp(k0, "price", 5)) fi = 4;
          break;
        case 6:
          if (!std::memcmp(k0, "action", 6)) fi = 0;
          break;
      }
      if (p < end && *p == 'n') {
        if (end - p < 4 || std::memcmp(p, "null", 4)) return -(li + 1);
        p += 4;
        // null: value fields -> 0 (Jackson primitive default),
        // next/prev -> unset; LAST occurrence wins either way
        if (fi >= 0) {
          v[fi] = 0;
          has[fi] = 0;
        }
      } else {
        int64_t x;
        if (!parse_int(p, end, &x)) return -(li + 1);
        if (fi >= 0) {
          v[fi] = x;
          has[fi] = 1;
        }
      }
      skip_ws(p, end);
    }
    skip_ws(p, end);
    if (p != end) return -(li + 1);  // trailing garbage
    if (p < bend) p++;               // consume '\n'
    for (int f = 0; f < 8; f++) P.cols[f][li] = v[f];
    P.hnext[li] = has[6];
    P.hprev[li] = has[7];
    P.tidcol[li] = 0;
    P.htid[li] = 0;
    P.n++;
  }
  return P.n;
}

// ---------------------------------------------------------------------------
// Binary order frames (wire.py layout authority): 72 bytes little-
// endian — magic 0xB1, version, kind, flags, u32 length prefix, then
// action/oid/aid/sid/price/size/next/prev as int64. Values are
// memcpy'd (alignment-safe); the build targets little-endian hosts
// only, same assumption the journal's binary framing already makes.

int64_t kme_parse_err_off(void* p) {
  return static_cast<Parse*>(p)->err_off;
}

// Parse `len` bytes of concatenated binary order frames into the same
// columns kme_parse_lines fills. Returns the frame count, or a
// negative validation code for the FIRST bad frame (offset readable
// via kme_parse_err_off): -1 truncated, -2 bad magic, -3 version
// skew, -4 bad kind, -5 bad length. Check order matches
// wire._check_frame_header exactly — the Python caller re-raises
// through the Python authority so the surfaced error is identical.
int64_t kme_parse_frames(void* handle, const uint8_t* buf, int64_t len) {
  // Flags bit 2 (FLAG_TID) extends the frame by a trailing int64 trace
  // word: 80 bytes instead of 72. The word is transport-advisory — it
  // never reaches the canonical JSON emission (kme_parse_emit).
  constexpr int64_t FRAME_SIZE = 72, FRAME_HDR = 8;
  constexpr int64_t FRAME_SIZE_TRACED = 80;
  Parse& P = *static_cast<Parse*>(handle);
  parse_reserve(P, len / FRAME_SIZE + 1);
  P.n = 0;
  P.err_off = 0;
  int64_t off = 0, i = 0;
  while (off < len) {
    P.err_off = off;
    const uint8_t* b = buf + off;
    int64_t rem = len - off;
    if (rem < FRAME_HDR) return -1;
    if (b[0] != 0xB1) return -2;
    if (b[1] != 1) return -3;
    if (b[2] != 0) return -4;
    const bool traced = (b[3] & 4) != 0;
    const int64_t expected = traced ? FRAME_SIZE_TRACED : FRAME_SIZE;
    uint32_t length;
    std::memcpy(&length, b + 4, 4);
    if (length != expected) return -5;
    if (rem < expected) return -1;
    int64_t v[8];
    std::memcpy(v, b + 8, 64);
    for (int f = 0; f < 8; f++) P.cols[f][i] = v[f];
    P.hnext[i] = b[3] & 1;
    P.hprev[i] = (b[3] >> 1) & 1;
    if (traced) {
      std::memcpy(&P.tidcol[i], b + FRAME_SIZE, 8);
      P.htid[i] = 1;
    } else {
      P.tidcol[i] = 0;
      P.htid[i] = 0;
    }
    off += expected;
    i++;
  }
  P.n = i;
  return i;
}

// Emit the canonical Jackson JSON line for every parsed row (the value
// the broker stores — binary is transport-only, the durable log and
// the oracle replay see order_json bytes regardless of encoding).
// Lines are concatenated with NO separators; kme_parse_emit_off gives
// n+1 offsets. Goes through put_order, the same emitter the byte-
// pinned reconstruction uses, so encode parity is inherited.
int64_t kme_parse_emit(void* handle) {
  Parse& P = *static_cast<Parse*>(handle);
  Recon& r = P.emit;
  // worst case per line: 65 bytes of scaffolding + 8 fields of up to
  // 20 chars (int64 min) = 225; 240 leaves slack
  int64_t need = 240 * (P.n > 0 ? P.n : 1);
  if (r.cap < need) {
    delete[] r.buf;
    r.buf = new char[need];
    r.cap = need;
  }
  if (P.emit_off_cap < P.n + 1) {
    delete[] P.emit_off;
    P.emit_off = new int64_t[P.n + 1];
    P.emit_off_cap = P.n + 1;
  }
  r.len = 0;
  for (int64_t i = 0; i < P.n; i++) {
    P.emit_off[i] = r.len;
    put_order(r, P.cols[0][i], P.cols[1][i], P.cols[2][i], P.cols[3][i],
              P.cols[4][i], P.cols[5][i], P.hnext[i] != 0, P.cols[6][i],
              P.hprev[i] != 0, P.cols[7][i]);
  }
  P.emit_off[P.n] = r.len;
  return r.len;
}

const char* kme_parse_emit_buf(void* p) {
  return static_cast<Parse*>(p)->emit.buf;
}
const int64_t* kme_parse_emit_off(void* p) {
  return static_cast<Parse*>(p)->emit_off;
}

}  // extern "C"
