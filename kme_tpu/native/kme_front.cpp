// Native front-door acceptor: the one-C-call-per-batch ingress stage.
//
// The PR 6 host path showed the idiom (kme_plan_batch: one call plans
// and packs a whole batch); this file applies it to the front of the
// pipeline. kme_front_accept validates a buffer of binary order
// frames, computes the rendezvous group route for every row
// (kme_group_assign, PR 9), and — when given pack/router handles —
// chains straight into kme_plan_batch to emit the (K,B) scan planes.
// The GIL is taken once per batch instead of once per order; Python
// only reads back column/group pointers.
//
// Everything here delegates to the existing single authorities in this
// shared object: frame validation + decode live in kme_wire.cpp
// (kme_parse_frames), group choice in kme_router.cpp
// (kme_group_assign), planning in kme_host.cpp (kme_plan_batch).
// The byte-exact Python twin is bridge/front.py accept_frames.
//
// Built together with the other sources by kme_tpu/native/__init__.py.

#include <cstdint>
#include <vector>

extern "C" {
// same shared object, other translation units
void* kme_parse_new();
void kme_parse_free(void*);
int64_t kme_parse_frames(void*, const uint8_t*, int64_t);
int64_t kme_parse_err_off(void*);
const int64_t* kme_parse_col(void*, int32_t);
const uint8_t* kme_parse_hnext(void*);
const uint8_t* kme_parse_hprev(void*);
const int64_t* kme_parse_tid(void*);
const uint8_t* kme_parse_htid(void*);
int64_t kme_parse_emit(void*);
const char* kme_parse_emit_buf(void*);
const int64_t* kme_parse_emit_off(void*);
void kme_group_assign(int64_t, const int64_t*, int32_t, int64_t,
                      int32_t*);
int64_t kme_plan_batch(void*, void*, int64_t, const int64_t*,
                       const int64_t*, const int64_t*, const int64_t*,
                       const int64_t*, const int64_t*, int32_t);
}

namespace {

struct Front {
  void* parse;
  std::vector<int64_t> keys;
  std::vector<int32_t> gsym, gacct, groups;
  int64_t plan_k = 0;
  Front() : parse(kme_parse_new()) {}
  ~Front() { kme_parse_free(parse); }
};

// symbol_key (bridge/front.py): abs with INT64_MIN passthrough.
inline int64_t symbol_key(int64_t sid) {
  return (sid < 0 && sid != INT64_MIN) ? -sid : sid;
}

}  // namespace

extern "C" {

void* kme_front_new() { return new Front(); }
void kme_front_free(void* p) { delete static_cast<Front*>(p); }

// Validate + decode + group-route one buffer of binary frames, and
// (when pack/router are non-null) plan+pack the batch in the same
// call. Returns the row count, or the negative kme_parse_frames
// validation code (-1..-5; offending offset via kme_front_err_off).
// The plan result K (incl. its negative capacity/envelope codes) is
// read via kme_front_plan_k, NOT the return value — a plan refusal
// still leaves valid columns/groups for the caller to re-route.
//
// Routing-key choice mirrors front.py route_line: account ops
// (CREATE=100 / TRANSFER=101) route by aid under salt_acct; CANCEL=4
// routes by oid and everything else by symbol_key(sid), both under
// salt_sym. Both assignments are computed full-width by the single
// authority kme_group_assign, then selected per row.
int64_t kme_front_accept(void* h, const uint8_t* buf, int64_t len,
                         int32_t ngroups, int64_t salt_sym,
                         int64_t salt_acct, void* pack, void* router,
                         int32_t B) {
  Front& F = *static_cast<Front*>(h);
  F.plan_k = 0;
  int64_t n = kme_parse_frames(F.parse, buf, len);
  if (n < 0) return n;
  const int64_t* act = kme_parse_col(F.parse, 0);
  const int64_t* oid = kme_parse_col(F.parse, 1);
  const int64_t* aid = kme_parse_col(F.parse, 2);
  const int64_t* sid = kme_parse_col(F.parse, 3);
  F.keys.resize(n);
  F.gsym.resize(n);
  F.gacct.resize(n);
  F.groups.resize(n);
  for (int64_t i = 0; i < n; i++)
    F.keys[i] = act[i] == 4 ? oid[i] : symbol_key(sid[i]);
  kme_group_assign(n, F.keys.data(), ngroups, salt_sym, F.gsym.data());
  kme_group_assign(n, aid, ngroups, salt_acct, F.gacct.data());
  for (int64_t i = 0; i < n; i++)
    F.groups[i] = (act[i] == 100 || act[i] == 101) ? F.gacct[i]
                                                   : F.gsym[i];
  if (pack && router)
    F.plan_k = kme_plan_batch(pack, router, n, act, oid, aid, sid,
                              kme_parse_col(F.parse, 4),
                              kme_parse_col(F.parse, 5), B);
  return n;
}

const int32_t* kme_front_groups(void* p) {
  return static_cast<Front*>(p)->groups.data();
}
int64_t kme_front_plan_k(void* p) {
  return static_cast<Front*>(p)->plan_k;
}
int64_t kme_front_err_off(void* p) {
  return kme_parse_err_off(static_cast<Front*>(p)->parse);
}
const int64_t* kme_front_col(void* p, int32_t i) {
  return kme_parse_col(static_cast<Front*>(p)->parse, i);
}
const uint8_t* kme_front_hnext(void* p) {
  return kme_parse_hnext(static_cast<Front*>(p)->parse);
}
const uint8_t* kme_front_hprev(void* p) {
  return kme_parse_hprev(static_cast<Front*>(p)->parse);
}
const int64_t* kme_front_tid(void* p) {
  return kme_parse_tid(static_cast<Front*>(p)->parse);
}
const uint8_t* kme_front_htid(void* p) {
  return kme_parse_htid(static_cast<Front*>(p)->parse);
}
// Canonical-JSON emission for the accepted rows (broker value bytes);
// delegates to the pinned kme_wire.cpp emitter.
int64_t kme_front_json(void* p) {
  return kme_parse_emit(static_cast<Front*>(p)->parse);
}
const char* kme_front_json_buf(void* p) {
  return kme_parse_emit_buf(static_cast<Front*>(p)->parse);
}
const int64_t* kme_front_json_off(void* p) {
  return kme_parse_emit_off(static_cast<Front*>(p)->parse);
}

}  // extern "C"
