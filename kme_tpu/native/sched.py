"""NativeScheduler: the C++ conflict-free scheduler behind the Python
Scheduler API.

Drop-in for kme_tpu.runtime.sequencer.Scheduler (which remains the
semantics authority and the fallback): identical plans field-for-field
(tests/test_native_sched.py), identical id-space state surface
(aid_idx / sid_lane / oid_sid / _rr_lane as properties backed by the
C++ maps, so checkpoint save/restore works unchanged).

One deliberate difference: the wire envelope (int32 price/size) is
validated for the WHOLE batch up front, so an EnvelopeError leaves the
id maps untouched (the Python fallback mutates them up to the offending
message); both raise on the same streams.
"""

from __future__ import annotations

import ctypes
from typing import Dict, List, Sequence

import numpy as np

from kme_tpu.native import BoundaryError, check_buffer, load_library
from kme_tpu.runtime.sequencer import (
    Barrier, EnvelopeError, CapacityError, HostReject, Schedule,
)
from kme_tpu.wire import OrderMsg

_ST_OK, _ST_CAP_ACCOUNTS, _ST_CAP_SYMBOLS = 0, 1, 2


def native_available() -> bool:
    return load_library() is not None


def _arr(ptr, n, dtype):
    if n == 0:
        return np.zeros(0, dtype)
    return np.ctypeslib.as_array(ptr, shape=(n,)).astype(dtype, copy=True)


class NativeScheduler:
    def __init__(self, num_lanes: int, num_accounts: int,
                 width: int = 0) -> None:
        if width < 0:
            raise ValueError(f"width must be >= 0, got {width}")
        self._lib = load_library()
        if self._lib is None:
            raise RuntimeError("native scheduler library unavailable")
        self.S = num_lanes
        self.A = num_accounts
        self.width = width
        self._h = self._lib.kme_sched_new(num_lanes, num_accounts, width)

    def __del__(self):
        lib, h = getattr(self, "_lib", None), getattr(self, "_h", None)
        if lib is not None and h:
            lib.kme_sched_free(h)
            self._h = None

    # -- planning ----------------------------------------------------------

    def plan(self, msgs: Sequence[OrderMsg]) -> Schedule:
        from kme_tpu.oracle import javalong as jl

        n = len(msgs)
        la, lo_, ld, ls, lp, lz = [], [], [], [], [], []
        jlong = jl.jlong
        for i, m in enumerate(msgs):
            if not (-2**31 <= m.price < 2**31 and -2**31 <= m.size < 2**31):
                raise EnvelopeError(
                    f"message {i}: price/size outside int32 "
                    f"(price={m.price}, size={m.size})")
            # action is compared RAW against the opcode table (matching
            # the Python fallback): out-of-int64 actions are unknown
            # opcodes, never aliased by wrapping. Ids wrap to Java longs
            # exactly like the Python scheduler's map keys.
            a = m.action
            la.append(a if -2**63 <= a < 2**63 else -1)
            lo_.append(jlong(m.oid))
            ld.append(jlong(m.aid))
            ls.append(jlong(m.sid))
            lp.append(m.price)
            lz.append(m.size)
        arrs = [np.array(l, np.int64) if l else np.zeros(0, np.int64)
                for l in (la, lo_, ld, ls, lp, lz)]
        P64 = ctypes.POINTER(ctypes.c_int64)
        ptrs = [c.ctypes.data_as(P64) for c in arrs]
        st = self._lib.kme_sched_plan(self._h, n, *ptrs)
        if st == _ST_CAP_ACCOUNTS:
            raise CapacityError(
                f"account capacity {self.A} exhausted "
                f"(aid={self._lib.kme_sched_err_value(self._h)})")
        if st == _ST_CAP_SYMBOLS:
            raise CapacityError(
                f"symbol capacity {self.S} exhausted "
                f"(sid={self._lib.kme_sched_err_value(self._h)})")

        lib, h = self._lib, self._h
        np_ = lib.kme_sched_n_placed(h)
        cols = {
            "msg_index": _arr(lib.kme_sched_p_msg(h), np_, np.int64),
            "segment": _arr(lib.kme_sched_p_seg(h), np_, np.int32),
            "step": _arr(lib.kme_sched_p_step(h), np_, np.int32),
            "lane": _arr(lib.kme_sched_p_lane(h), np_, np.int32),
            "act": _arr(lib.kme_sched_p_act(h), np_, np.int32),
            "aidx": _arr(lib.kme_sched_p_aidx(h), np_, np.int32),
            "oid": _arr(lib.kme_sched_p_oid(h), np_, np.int64),
            "price": _arr(lib.kme_sched_p_price(h), np_, np.int32),
            "size": _arr(lib.kme_sched_p_size(h), np_, np.int32),
            "slot": _arr(lib.kme_sched_p_slot(h), np_, np.int32),
        }
        nb = lib.kme_sched_n_barriers(h)
        b_msg = _arr(lib.kme_sched_b_msg(h), nb, np.int64)
        b_lane = _arr(lib.kme_sched_b_lane(h), nb, np.int32)
        b_mode = _arr(lib.kme_sched_b_mode(h), nb, np.int32)
        b_credit = _arr(lib.kme_sched_b_credit(h), nb, np.int64)
        barriers = [Barrier(int(b_msg[i]), int(b_lane[i]), int(b_mode[i]),
                            int(b_credit[i])) for i in range(nb)]
        nr = lib.kme_sched_n_rejects(h)
        rejects = [HostReject(int(x))
                   for x in _arr(lib.kme_sched_r_msg(h), nr, np.int64)]
        ns = lib.kme_sched_n_segments(h)
        seg_steps = _arr(lib.kme_sched_seg_steps(h), ns, np.int32).tolist()
        npr = lib.kme_sched_n_program(h)
        prog_raw = _arr(lib.kme_sched_program(h), npr * 2, np.int32)
        program = [("scan" if prog_raw[2 * i] == 0 else "barrier",
                    int(prog_raw[2 * i + 1])) for i in range(npr)]
        return Schedule(cols, barriers, rejects, seg_steps, program)

    # -- id-space state (same surface as the Python Scheduler) ------------

    @property
    def aid_idx(self) -> Dict[int, int]:
        n = self._lib.kme_sched_n_accounts(self._h)
        keys = np.zeros(n, np.int64)
        vals = np.zeros(n, np.int32)
        self._lib.kme_sched_export_accounts(
            self._h, keys.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            vals.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        return dict(zip(keys.tolist(), vals.tolist()))

    @aid_idx.setter
    def aid_idx(self, d: Dict[int, int]) -> None:
        keys = np.fromiter(d.keys(), np.int64, len(d))
        vals = np.fromiter(d.values(), np.int32, len(d))
        self._lib.kme_sched_import_accounts(
            self._h, len(d),
            keys.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            vals.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))

    @property
    def sid_lane(self) -> Dict[int, int]:
        n = self._lib.kme_sched_n_symbols(self._h)
        keys = np.zeros(n, np.int64)
        vals = np.zeros(n, np.int32)
        self._lib.kme_sched_export_symbols(
            self._h, keys.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            vals.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        return dict(zip(keys.tolist(), vals.tolist()))

    @sid_lane.setter
    def sid_lane(self, d: Dict[int, int]) -> None:
        keys = np.fromiter(d.keys(), np.int64, len(d))
        vals = np.fromiter(d.values(), np.int32, len(d))
        self._lib.kme_sched_import_symbols(
            self._h, len(d),
            keys.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            vals.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))

    @property
    def oid_sid(self) -> Dict[int, int]:
        n = self._lib.kme_sched_n_routes(self._h)
        keys = np.zeros(n, np.int64)
        vals = np.zeros(n, np.int64)
        self._lib.kme_sched_export_routes(
            self._h, keys.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            vals.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        return dict(zip(keys.tolist(), vals.tolist()))

    @oid_sid.setter
    def oid_sid(self, d: Dict[int, int]) -> None:
        keys = np.fromiter(d.keys(), np.int64, len(d))
        vals = np.fromiter(d.values(), np.int64, len(d))
        self._lib.kme_sched_import_routes(
            self._h, len(d),
            keys.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            vals.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))

    @property
    def _rr_lane(self) -> int:
        return int(self._lib.kme_sched_rr_lane(self._h))

    @_rr_lane.setter
    def _rr_lane(self, v: int) -> None:
        self._lib.kme_sched_set_rr_lane(self._h, int(v))

    # -- reconstruction helpers (same as Scheduler) ------------------------

    def acct_of_idx(self) -> List[int]:
        d = self.aid_idx
        out = [0] * len(d)
        for aid, idx in d.items():
            out[idx] = aid
        return out

    def sid_of_lane(self) -> Dict[int, int]:
        return {lane: sid for sid, lane in self.sid_lane.items()}


def apply_placement(perm, lanes, s_local: int):
    """Apply the mesh planner's elastic placement table to a routed
    lane column in one vectorized pass: global lane -> global slot
    (`perm[lane]`), then (shard, local_row) = divmod(slot, s_local).

    This is the host-path mirror of SeqMeshSession.plan_windows'
    placement application (parallel/seqmesh.py); like plan_batch /
    recon_batch above, its eventual native home is kme_host.cpp —
    the numpy fancy-index form here is the semantics authority and is
    already allocation-light enough for the planner's hot scope.
    Returns (slot, shard, local_row), each shaped like `lanes`."""
    lanes64 = lanes.astype(np.int64, copy=False)
    slot = perm[lanes64]
    return slot, slot // s_local, slot % s_local


def slice_windows(wins: dict, win_idx, shard: int, shards: int,
                  bw: int) -> dict:
    """Slice ONE shard's rows for a set of windows out of the stacked
    (K, shards*bw) i32 scan-input planes into dense (kpad, bw) per-field
    segment planes, zero-padded to a pow2 window count (padding rows
    are all-zero NOP windows, a no-op through the kernel). This is the
    per-shard submission-queue staging step of the seqmesh async
    dispatcher — hot scope: one native call per field (kme_shard_slice,
    kme_host.cpp) with a byte-exact numpy-view fallback, no implicit
    host syncs or allocations beyond the output planes."""
    from kme_tpu.utils import pow2_bucket

    n = len(win_idx)
    kpad = pow2_bucket(max(n, 1), lo=1)
    idx = np.fromiter(win_idx, np.int64, n)
    out = {}
    lib = load_library()
    if lib is not None and hasattr(lib, "kme_shard_slice"):
        P32 = ctypes.POINTER(ctypes.c_int32)
        P64 = ctypes.POINTER(ctypes.c_int64)
        iptr = idx.ctypes.data_as(P64)
        for f, v in wins.items():
            src = check_buffer(f"slice_windows.{f}", v.reshape(-1),
                               np.int32, v.shape[0] * shards * bw)
            dst = np.zeros((kpad, bw), np.int32)
            lib.kme_shard_slice(
                src.ctypes.data_as(P32), v.shape[0], shards, bw,
                shard, iptr, n, kpad, dst.ctypes.data_as(P32))
            out[f] = dst
        return out
    for f, v in wins.items():
        dst = np.zeros((kpad, bw), np.int32)
        if n:
            dst[:n] = v.reshape(v.shape[0], shards, bw)[idx, shard]
        out[f] = dst
    return out


# -- batch host-path entry points (one C++ call per stage) ----------------
#
# The serve/bench hot loop's host work — envelope check + route + H2D
# staging pack on the way in, output-array -> byte-stream reconstruction
# on the way out — as single C calls (kme_plan_batch / kme_recon_batch).
# Both return None when the loaded library predates the entry points so
# callers fall back to the numpy implementations, which remain the
# semantics authority (parity pinned by tests/test_host_path.py).


def plan_batch(router, batch, B: int):
    """Envelope-check + route + pack one WireBatch into the stacked
    (K, B) i32 scan-input planes in a single native call. `router` must
    be a NativeSeqRouter (the caller checks); returns
    (cols, host_rejects, stacked, cnts, K) with SeqSession._plan's
    exact contract, or None when unavailable. The stacked planes are
    zero-copy views into a rotating native buffer (4 deep): each is
    consumed by the very next jit dispatch, and double-buffered serving
    keeps at most two packed batches in flight."""
    lib = router._lib
    if not hasattr(lib, "kme_plan_batch"):
        return None
    pack = ensure_pack(router)
    # kme_plan_batch reads batch.n int64s from every column with no
    # native-side length check: pin the dtype at conversion and verify
    # the element count BEFORE handing out pointers
    raw = {f: check_buffer(
               f"plan_batch.{f}",
               np.ascontiguousarray(getattr(batch, f), np.int64),
               np.int64, batch.n)
           for f in ("action", "oid", "aid", "sid", "price", "size")}
    P64 = ctypes.POINTER(ctypes.c_int64)
    K = int(lib.kme_plan_batch(
        pack, router._h, batch.n,
        *(raw[f].ctypes.data_as(P64)
          for f in ("action", "oid", "aid", "sid", "price", "size")),
        B))
    return collect_plan(lib, router, pack, K, B, raw["price"],
                        raw["size"])


def ensure_pack(router):
    """The router's cached native pack handle (kme_pack_new), created
    on first use and freed with the router. Shared by plan_batch and
    the front-door acceptor (bridge/front.py accept_frames), which
    chains kme_plan_batch inside its single kme_front_accept call."""
    lib = router._lib
    pack = getattr(router, "_pack", None)
    if pack is None:
        import weakref

        pack = lib.kme_pack_new()
        router._pack = pack
        router._pack_fin = weakref.finalize(router, lib.kme_pack_free,
                                            pack)
    return pack


def collect_plan(lib, router, pack, K, B, price, size):
    """Shared tail of the native plan: map the result code K to the
    EnvelopeError/CapacityError contract and read back routed columns +
    packed planes. `price`/`size` are the int64 input columns,
    consulted only for the envelope error message."""
    if K == -3:
        i = int(lib.kme_pack_err_index(pack))
        raise EnvelopeError(
            f"message {i}: price/size outside int32 "
            f"(price={int(price[i])}, "
            f"size={int(size[i])})")
    if K < 0:
        raise CapacityError(
            f"{'account' if K == -1 else 'symbol'} capacity "
            f"exhausted (id={lib.kme_router_err_value(router._h)})")
    h = router._h
    nr = int(lib.kme_router_n_routed(h))
    nj = int(lib.kme_router_n_rejects(h))
    cols = {
        "msg_index": _arr(lib.kme_router_o_msg(h), nr, np.int64),
        "act": _arr(lib.kme_router_o_act(h), nr, np.int32),
        "aid": _arr(lib.kme_router_o_aidx(h), nr, np.int32),
        "price": _arr(lib.kme_router_o_price(h), nr, np.int32),
        "size": _arr(lib.kme_router_o_size(h), nr, np.int32),
        "lane": _arr(lib.kme_router_o_lane(h), nr, np.int32),
        "oid": _arr(lib.kme_router_o_oid(h), nr, np.int64),
    }
    host_rejects = set(_arr(lib.kme_router_o_rej(h), nj,
                            np.int64).tolist())
    planes = np.ctypeslib.as_array(lib.kme_pack_planes(pack),
                                   shape=(7, K, B))
    stacked = {name: planes[j] for j, name in enumerate(
        ("act", "aid", "price", "size", "lane", "oid_lo", "oid_hi"))}
    cnts = [max(min(B, nr - ci * B), 0) for ci in range(K)]
    return cols, host_rejects, stacked, cnts, K


def recon_batch(lib, handle, batch, cols, host, fills, lane_sid,
                idx2aid):
    """One-pass native reconstruction (kme_recon_batch): batch columns
    + routed rows + device results -> the byte-exact record stream,
    without the ~10 per-message numpy scatter arrays kme_recon_wire
    needs. Returns (buf, line_off, msg_lines) like
    SeqSession.process_wire_buffer, or None when unavailable."""
    if not hasattr(lib, "kme_recon_batch"):
        return None
    c = ctypes
    P64 = c.POINTER(c.c_int64)
    P32 = c.POINTER(c.c_int32)
    PU8 = c.POINTER(c.c_uint8)
    pp = lambda a, t: a.ctypes.data_as(t)
    i64 = lambda a: np.ascontiguousarray(a, np.int64)
    nmsg = batch.n
    nr = len(cols["msg_index"])
    # kme_recon_batch reads the m_* columns to nmsg and the r_*/h_*
    # rows to nr unconditionally (kme_wire.cpp): every pointer below is
    # validated for dtype/contiguity/length first, so a short or
    # mis-typed buffer raises here instead of overreading native-side
    for f in ("action", "oid", "aid", "sid", "price", "size", "next",
              "prev"):
        check_buffer(f"recon_batch.{f}", getattr(batch, f),
                     np.int64, nmsg)
    for f in ("hnext", "hprev"):
        check_buffer(f"recon_batch.{f}", getattr(batch, f),
                     np.uint8, nmsg)
    r_msg = i64(cols["msg_index"])
    r_act = np.ascontiguousarray(cols["act"], np.int32)
    r_lane = np.ascontiguousarray(cols["lane"], np.int32)
    h_ok = np.ascontiguousarray(host["ok"], np.uint8)
    h_append = np.ascontiguousarray(host["append"], np.uint8)
    h_nfill, h_resid, h_prev = (i64(host[k]) for k in
                                ("nfill", "residual", "prev_oid"))
    for nm, a in (("cols.act", r_act), ("cols.lane", r_lane)):
        check_buffer(f"recon_batch.{nm}", a, np.int32, nr)
    for nm, a in (("host.ok", h_ok), ("host.append", h_append)):
        check_buffer(f"recon_batch.{nm}", a, np.uint8, nr)
    for nm, a in (("host.nfill", h_nfill), ("host.residual", h_resid),
                  ("host.prev_oid", h_prev)):
        check_buffer(f"recon_batch.{nm}", a, np.int64, nr)
    check_buffer("recon_batch.lane_sid", lane_sid, np.int64)
    check_buffer("recon_batch.idx2aid", idx2aid, np.int64)
    if fills.ndim != 2 or fills.shape[0] != 4:
        raise BoundaryError(
            f"recon_batch.fills: expected shape (4, F), got "
            f"{fills.shape}")
    f_oid, f_aidx, f_price, f_size = (
        check_buffer(f"recon_batch.fills[{j}]", i64(fills[j]),
                     np.int64, fills.shape[1]) for j in range(4))
    rc = lib.kme_recon_batch(
        nmsg, pp(batch.action, P64), pp(batch.oid, P64),
        pp(batch.aid, P64), pp(batch.sid, P64), pp(batch.price, P64),
        pp(batch.size, P64), pp(batch.next, P64),
        pp(batch.hnext, PU8), pp(batch.prev, P64),
        pp(batch.hprev, PU8),
        nr, pp(r_msg, P64), pp(r_act, P32), pp(r_lane, P32),
        pp(h_ok, PU8), pp(h_nfill, P64), pp(h_resid, P64),
        pp(h_prev, P64), pp(h_append, PU8),
        len(lane_sid), pp(lane_sid, P64),
        len(idx2aid), pp(idx2aid, P64),
        fills.shape[1], pp(f_oid, P64), pp(f_aidx, P64),
        pp(f_price, P64), pp(f_size, P64), handle)
    if rc != 0:
        raise RuntimeError(f"kme_recon_batch failed rc={rc}")
    blen = lib.kme_recon_len(handle)
    nlines = lib.kme_recon_n_lines(handle)
    buf = c.string_at(lib.kme_recon_buf(handle), blen)
    line_off = np.empty(nlines + 1, np.int64)
    line_off[:nlines] = np.ctypeslib.as_array(
        lib.kme_recon_line_off(handle), (nlines,))
    line_off[nlines] = blen
    msg_lines = np.ctypeslib.as_array(
        lib.kme_recon_msg_lines(handle), (nmsg,)).copy()
    return buf, line_off, msg_lines
