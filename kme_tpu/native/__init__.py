"""Native host-runtime bindings: build-on-demand C++ via ctypes.

The C++ sources compile once per source hash with the system toolchain
(g++) into a cached shared object next to the package; everything
degrades gracefully to the pure-Python implementations when no compiler
is available (`load_library()` returns None). `KME_NATIVE=0` disables
the native path outright.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import sys
import tempfile
from typing import Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRCS = (os.path.join(_HERE, "kme_host.cpp"),
         os.path.join(_HERE, "kme_oracle.cpp"),
         os.path.join(_HERE, "kme_wire.cpp"),
         os.path.join(_HERE, "kme_router.cpp"),
         os.path.join(_HERE, "kme_front.cpp"))

_lib = None
_lib_tried = False


class BoundaryError(ValueError):
    """A buffer about to cross the ctypes boundary is the wrong shape,
    dtype, length, or layout. The C side reads exactly the lengths it
    is told (kme_wire.cpp reads m_* to nmsg and r_*/h_* to nr with no
    way to check), so a short or mis-typed buffer is a native-side
    overread — this is raised Python-side instead."""


def check_buffer(name, arr, dtype, n=None):
    """Validate one array for a native call: exact dtype, C-contiguous,
    1-D, and (when given) at least `n` elements. Returns the array so
    call sites can validate inline."""
    import numpy as np

    if not isinstance(arr, np.ndarray):
        raise BoundaryError(
            f"{name}: expected ndarray, got {type(arr).__name__}")
    if arr.dtype != np.dtype(dtype):
        raise BoundaryError(
            f"{name}: dtype {arr.dtype} != required {np.dtype(dtype)}")
    if arr.ndim != 1:
        raise BoundaryError(f"{name}: expected 1-D, got shape "
                            f"{arr.shape}")
    if not arr.flags["C_CONTIGUOUS"]:
        raise BoundaryError(f"{name}: buffer is not C-contiguous")
    if n is not None and arr.shape[0] < n:
        raise BoundaryError(
            f"{name}: {arr.shape[0]} element(s), native call reads "
            f"{n} — short buffer would be an overread")
    return arr


def _build(srcs, out: str) -> bool:
    cmd = (["g++", "-O3", "-shared", "-fPIC", "-std=c++17"] + list(srcs)
           + ["-o", out])
    try:
        r = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
    except (OSError, subprocess.TimeoutExpired) as e:
        print(f"kme_tpu.native: build failed ({e}); using the pure-Python "
              f"fallback", file=sys.stderr)
        return False
    if r.returncode != 0:
        print(f"kme_tpu.native: g++ failed:\n{r.stderr[:2000]}\n"
              f"using the pure-Python fallback", file=sys.stderr)
        return False
    return True


def load_library() -> Optional[ctypes.CDLL]:
    """The compiled host-runtime library, building it if needed.
    None when disabled or unbuildable (callers fall back to Python)."""
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    if os.environ.get("KME_NATIVE", "1") == "0":
        return None
    override = os.environ.get("KME_NATIVE_SO")
    if override:
        # explicit prebuilt library (sanitizer runs: scripts/
        # build_native.py --sanitize emits an ASan/UBSan .so whose tag
        # can't live in the normal cache); missing/unloadable is an
        # ERROR, not a fallback — a sanitizer run that silently used
        # the plain build would prove nothing
        try:
            _lib = _bind(ctypes.CDLL(override))
        except OSError as e:
            raise OSError(
                f"KME_NATIVE_SO={override} could not be loaded: {e}")
        return _lib
    try:
        h = hashlib.sha256()
        for src in _SRCS:
            with open(src, "rb") as f:
                h.update(f.read())
        tag = h.hexdigest()[:16]
    except OSError as e:
        print(f"kme_tpu.native: source unreadable ({e}); the native "
              f"runtime is DISABLED — using the pure-Python fallbacks",
              file=sys.stderr)
        return None
    build_dir = os.path.join(_HERE, "_build")
    so_path = os.path.join(build_dir, f"kme_host_{tag}.so")
    if not os.path.exists(so_path):
        try:
            os.makedirs(build_dir, exist_ok=True)
            # build into a temp name then rename: concurrent processes
            # race benignly (os.replace is atomic)
            fd, tmp = tempfile.mkstemp(suffix=".so", dir=build_dir)
            os.close(fd)
            built = _build(_SRCS, tmp)
            if built:
                os.replace(tmp, so_path)
            else:
                os.unlink(tmp)
                return None
        except OSError as e:  # read-only install dir etc.
            print(f"kme_tpu.native: cannot build ({e}); using the "
                  f"pure-Python fallback", file=sys.stderr)
            return None
    try:
        _lib = _bind(ctypes.CDLL(so_path))
    except OSError as e:
        print(f"kme_tpu.native: dlopen failed ({e}); using the pure-Python "
              f"fallback", file=sys.stderr)
        _lib = None
    return _lib


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    c = ctypes
    P64, P32 = c.POINTER(c.c_int64), c.POINTER(c.c_int32)
    sigs = {
        "kme_sched_new": ([c.c_int32, c.c_int32, c.c_int32], c.c_void_p),
        "kme_sched_free": ([c.c_void_p], None),
        "kme_sched_plan": ([c.c_void_p, c.c_int64] + [P64] * 6, c.c_int32),
        "kme_sched_n_placed": ([c.c_void_p], c.c_int64),
        "kme_sched_p_msg": ([c.c_void_p], P64),
        "kme_sched_p_seg": ([c.c_void_p], P32),
        "kme_sched_p_step": ([c.c_void_p], P32),
        "kme_sched_p_lane": ([c.c_void_p], P32),
        "kme_sched_p_act": ([c.c_void_p], P32),
        "kme_sched_p_aidx": ([c.c_void_p], P32),
        "kme_sched_p_oid": ([c.c_void_p], P64),
        "kme_sched_p_price": ([c.c_void_p], P32),
        "kme_sched_p_size": ([c.c_void_p], P32),
        "kme_sched_p_slot": ([c.c_void_p], P32),
        "kme_sched_n_barriers": ([c.c_void_p], c.c_int64),
        "kme_sched_b_msg": ([c.c_void_p], P64),
        "kme_sched_b_lane": ([c.c_void_p], P32),
        "kme_sched_b_mode": ([c.c_void_p], P32),
        "kme_sched_b_credit": ([c.c_void_p], P64),
        "kme_sched_n_rejects": ([c.c_void_p], c.c_int64),
        "kme_sched_r_msg": ([c.c_void_p], P64),
        "kme_sched_n_segments": ([c.c_void_p], c.c_int64),
        "kme_sched_seg_steps": ([c.c_void_p], P32),
        "kme_sched_n_program": ([c.c_void_p], c.c_int64),
        "kme_sched_program": ([c.c_void_p], P32),
        "kme_sched_err_value": ([c.c_void_p], c.c_int64),
        "kme_sched_n_accounts": ([c.c_void_p], c.c_int64),
        "kme_sched_n_symbols": ([c.c_void_p], c.c_int64),
        "kme_sched_n_routes": ([c.c_void_p], c.c_int64),
        "kme_sched_rr_lane": ([c.c_void_p], c.c_int32),
        "kme_sched_set_rr_lane": ([c.c_void_p, c.c_int32], None),
        "kme_sched_export_accounts": ([c.c_void_p, P64, P32], None),
        "kme_sched_export_symbols": ([c.c_void_p, P64, P32], None),
        "kme_sched_export_routes": ([c.c_void_p, P64, P64], None),
        "kme_sched_import_accounts": ([c.c_void_p, c.c_int64, P64, P32], None),
        "kme_sched_import_symbols": ([c.c_void_p, c.c_int64, P64, P32], None),
        "kme_sched_import_routes": ([c.c_void_p, c.c_int64, P64, P64], None),
        # native quirk-exact engine (kme_oracle.cpp)
        "kme_oracle_new": ([c.c_int32, c.c_int32, c.c_int64, c.c_int32,
                            c.c_int64], c.c_void_p),
        "kme_oracle_free": ([c.c_void_p], None),
        "kme_oracle_process": ([c.c_void_p, c.c_int64] + [P64] * 6
                               + [P64, c.POINTER(c.c_uint8),
                                  P64, c.POINTER(c.c_uint8)], c.c_int32),
        "kme_oracle_err_index": ([c.c_void_p], c.c_int64),
        "kme_oracle_err_msg": ([c.c_void_p], c.c_char_p),
        "kme_oracle_out_buf": ([c.c_void_p], c.c_void_p),
        "kme_oracle_out_len": ([c.c_void_p], c.c_int64),
        "kme_oracle_line_counts": ([c.c_void_p], P64),
        "kme_oracle_n_processed": ([c.c_void_p], c.c_int64),
        "kme_oracle_dump_state": ([c.c_void_p], c.c_char_p),
        "kme_oracle_load_state": ([c.c_void_p, c.c_char_p], c.c_int32),
        # native seq router (kme_router.cpp)
        "kme_router_new": ([c.c_int64, c.c_int64], c.c_void_p),
        "kme_router_free": ([c.c_void_p], None),
        "kme_router_route": ([c.c_void_p, c.c_int64] + [P64] * 6,
                             c.c_int32),
        "kme_router_n_routed": ([c.c_void_p], c.c_int64),
        "kme_router_n_rejects": ([c.c_void_p], c.c_int64),
        "kme_router_err_value": ([c.c_void_p], c.c_int64),
        "kme_router_o_msg": ([c.c_void_p], P64),
        "kme_router_o_oid": ([c.c_void_p], P64),
        "kme_router_o_act": ([c.c_void_p], P32),
        "kme_router_o_aidx": ([c.c_void_p], P32),
        "kme_router_o_price": ([c.c_void_p], P32),
        "kme_router_o_size": ([c.c_void_p], P32),
        "kme_router_o_lane": ([c.c_void_p], P32),
        "kme_router_o_rej": ([c.c_void_p], P64),
        "kme_router_n_accounts": ([c.c_void_p], c.c_int64),
        "kme_router_n_symbols": ([c.c_void_p], c.c_int64),
        "kme_router_n_routes": ([c.c_void_p], c.c_int64),
        "kme_router_export_accounts": ([c.c_void_p, P64, P32], None),
        "kme_router_export_symbols": ([c.c_void_p, P64, P32], None),
        "kme_router_export_routes": ([c.c_void_p, P64, P64], None),
        "kme_router_import_accounts": ([c.c_void_p, c.c_int64, P64, P32],
                                       None),
        "kme_router_import_symbols": ([c.c_void_p, c.c_int64, P64, P32],
                                      None),
        "kme_router_import_routes": ([c.c_void_p, c.c_int64, P64, P64],
                                     None),
        # consistent-hash group assignment (kme_router.cpp, stateless)
        "kme_group_assign": ([c.c_int64, P64, c.c_int32, c.c_int64,
                              P32], None),
        # native wire reconstruction (kme_wire.cpp)
        "kme_recon_new": ([], c.c_void_p),
        "kme_recon_free": ([c.c_void_p], None),
        "kme_recon_buf": ([c.c_void_p], c.c_void_p),
        "kme_recon_len": ([c.c_void_p], c.c_int64),
        "kme_recon_n_lines": ([c.c_void_p], c.c_int64),
        "kme_recon_line_off": ([c.c_void_p], P64),
        "kme_recon_msg_lines": ([c.c_void_p], P32),
        "kme_recon_wire": ([c.c_int64] + [P64] * 6
                           + [P64, c.POINTER(c.c_uint8)] * 2
                           + [c.POINTER(c.c_uint8), P32,
                              c.POINTER(c.c_uint8), P32, P64, P64, P64,
                              c.POINTER(c.c_uint8), P64]
                           + [c.c_int64] + [P64] * 4 + [c.c_void_p],
                           c.c_int32),
        # native batch plan + H2D pack (kme_host.cpp kme_pack_*)
        "kme_pack_new": ([], c.c_void_p),
        "kme_pack_free": ([c.c_void_p], None),
        "kme_plan_batch": ([c.c_void_p, c.c_void_p, c.c_int64]
                           + [P64] * 6 + [c.c_int32], c.c_int64),
        "kme_pack_planes": ([c.c_void_p], P32),
        "kme_pack_err_index": ([c.c_void_p], c.c_int64),
        # per-shard async-dispatch window slicing (kme_host.cpp)
        "kme_shard_slice": ([P32] + [c.c_int64] * 4 + [P64]
                            + [c.c_int64] * 2 + [P32], None),
        # native one-pass batch reconstruction (kme_wire.cpp)
        "kme_recon_batch": ([c.c_int64] + [P64] * 6
                            + [P64, c.POINTER(c.c_uint8)] * 2
                            + [c.c_int64, P64, P32, P32]
                            + [c.POINTER(c.c_uint8), P64, P64, P64,
                               c.POINTER(c.c_uint8)]
                            + [c.c_int64, P64, c.c_int64, P64]
                            + [c.c_int64] + [P64] * 4 + [c.c_void_p],
                            c.c_int32),
        # native wire parsing (kme_wire.cpp kme_parse_*)
        "kme_parse_new": ([], c.c_void_p),
        "kme_parse_free": ([c.c_void_p], None),
        "kme_parse_lines": ([c.c_void_p, c.c_char_p, c.c_int64],
                            c.c_int64),
        "kme_parse_col": ([c.c_void_p, c.c_int32], P64),
        "kme_parse_hnext": ([c.c_void_p], c.POINTER(c.c_uint8)),
        "kme_parse_hprev": ([c.c_void_p], c.POINTER(c.c_uint8)),
        "kme_parse_tid": ([c.c_void_p], P64),
        "kme_parse_htid": ([c.c_void_p], c.POINTER(c.c_uint8)),
        # binary order frames + canonical-JSON emission (kme_wire.cpp)
        "kme_parse_frames": ([c.c_void_p, c.c_char_p, c.c_int64],
                             c.c_int64),
        "kme_parse_err_off": ([c.c_void_p], c.c_int64),
        "kme_parse_emit": ([c.c_void_p], c.c_int64),
        "kme_parse_emit_buf": ([c.c_void_p], c.c_void_p),
        "kme_parse_emit_off": ([c.c_void_p], P64),
        # native front-door acceptor (kme_front.cpp): validate + route
        # + plan in one call per batch
        "kme_front_new": ([], c.c_void_p),
        "kme_front_free": ([c.c_void_p], None),
        "kme_front_accept": ([c.c_void_p, c.c_char_p, c.c_int64,
                              c.c_int32, c.c_int64, c.c_int64,
                              c.c_void_p, c.c_void_p, c.c_int32],
                             c.c_int64),
        "kme_front_groups": ([c.c_void_p], P32),
        "kme_front_plan_k": ([c.c_void_p], c.c_int64),
        "kme_front_err_off": ([c.c_void_p], c.c_int64),
        "kme_front_col": ([c.c_void_p, c.c_int32], P64),
        "kme_front_hnext": ([c.c_void_p], c.POINTER(c.c_uint8)),
        "kme_front_hprev": ([c.c_void_p], c.POINTER(c.c_uint8)),
        "kme_front_tid": ([c.c_void_p], P64),
        "kme_front_htid": ([c.c_void_p], c.POINTER(c.c_uint8)),
        "kme_front_json": ([c.c_void_p], c.c_int64),
        "kme_front_json_buf": ([c.c_void_p], c.c_void_p),
        "kme_front_json_off": ([c.c_void_p], P64),
    }
    for name, (argtypes, restype) in sigs.items():
        fn = getattr(lib, name)
        fn.argtypes = argtypes
        fn.restype = restype
    return lib
