"""NativeOracleEngine: the C++ quirk-exact engine behind the oracle API.

The fast quirk-exact serving path (COMPAT.md: the parallel engine cannot
be quirk-exact under Q11, and the serial device replica is op-count
bound on TPU) — the same semantics as kme_tpu.oracle.OracleEngine, at
native speed. Byte parity (wire lines AND deep store state) is pinned by
tests/test_native_oracle.py.

Envelope: ids are Java longs (wrapped at this marshal boundary — the
Jackson long envelope), price/size int32 (EnvelopeError beyond).
Reference-death paths raise the oracle's ReferenceHang/ReferenceCrash
with the engine state left at the death point, like the oracle.
"""

from __future__ import annotations

import ctypes
from typing import List, Optional, Sequence

import numpy as np

from kme_tpu.native import load_library
from kme_tpu.oracle.engine import ReferenceCrash, ReferenceHang
from kme_tpu.wire import OrderMsg

_ERR_HANG, _ERR_CRASH = 1, 2


def native_available() -> bool:
    return load_library() is not None


class NativeOracleEngine:
    def __init__(self, compat: str = "java",
                 book_slots: Optional[int] = None,
                 max_fills: Optional[int] = None) -> None:
        if compat not in ("java", "fixed"):
            raise ValueError(compat)
        self.java = compat == "java"
        self.book_slots = book_slots
        self.max_fills = max_fills
        if self.java and (book_slots is not None or max_fills is not None):
            raise ValueError("capacity envelope is a fixed-mode concept")
        self._lib = load_library()
        if self._lib is None:
            raise RuntimeError("native engine library unavailable")
        self._h = self._lib.kme_oracle_new(
            1 if self.java else 0,
            0 if book_slots is None else 1, book_slots or 0,
            0 if max_fills is None else 1, max_fills or 0)

    def __del__(self):
        lib, h = getattr(self, "_lib", None), getattr(self, "_h", None)
        if lib is not None and h:
            lib.kme_oracle_free(h)
            self._h = None

    def process_wire(self, msgs: Sequence[OrderMsg]) -> List[List[str]]:
        """Per-message `<key> <json>` wire-line lists, byte-identical to
        [r.wire() for r in OracleEngine.process(m)]. Raises the oracle's
        ReferenceHang/ReferenceCrash on a reference-death message (lines
        of earlier messages are lost to the caller — use
        process_wire_partial to retain them, as the service does)."""
        out, exc = self.process_wire_partial(msgs)
        if exc is not None:
            raise exc
        return out

    def process_wire_partial(self, msgs: Sequence[OrderMsg]):
        """Like process_wire, but on a reference-death message returns
        (lines_of_completed_messages, exception) instead of discarding
        the completed prefix — the byte-faithful service path (the
        reference forwards every record before its thread dies)."""
        from kme_tpu.oracle import javalong as jl
        from kme_tpu.runtime.sequencer import EnvelopeError

        n = len(msgs)
        cols = {k: [] for k in ("action", "oid", "aid", "sid", "price",
                                "size", "next", "prev")}
        nxt_has = np.zeros(n, np.uint8)
        prv_has = np.zeros(n, np.uint8)
        jlong = jl.jlong
        for i, m in enumerate(msgs):
            if not (-2**31 <= m.price < 2**31 and -2**31 <= m.size < 2**31):
                raise EnvelopeError(
                    f"message {i}: price/size outside int32 "
                    f"(price={m.price}, size={m.size})")
            a = m.action
            cols["action"].append(a if -2**63 <= a < 2**63 else -1)
            cols["oid"].append(jlong(m.oid))
            cols["aid"].append(jlong(m.aid))
            cols["sid"].append(jlong(m.sid))
            cols["price"].append(m.price)
            cols["size"].append(m.size)
            cols["next"].append(0 if m.next is None else jlong(m.next))
            cols["prev"].append(0 if m.prev is None else jlong(m.prev))
            if m.next is not None:
                nxt_has[i] = 1
            if m.prev is not None:
                prv_has[i] = 1
        arrs = [np.array(cols[k], np.int64) if n else np.zeros(0, np.int64)
                for k in ("action", "oid", "aid", "sid", "price", "size",
                          "next", "prev")]
        P64 = ctypes.POINTER(ctypes.c_int64)
        P8 = ctypes.POINTER(ctypes.c_uint8)
        lib, h = self._lib, self._h
        rc = lib.kme_oracle_process(
            h, n, arrs[0].ctypes.data_as(P64), arrs[1].ctypes.data_as(P64),
            arrs[2].ctypes.data_as(P64), arrs[3].ctypes.data_as(P64),
            arrs[4].ctypes.data_as(P64), arrs[5].ctypes.data_as(P64),
            arrs[6].ctypes.data_as(P64), nxt_has.ctypes.data_as(P8),
            arrs[7].ctypes.data_as(P64), prv_has.ctypes.data_as(P8))
        exc = None
        if rc == _ERR_HANG:
            exc = ReferenceHang(
                f"message {lib.kme_oracle_err_index(h)}: "
                f"{lib.kme_oracle_err_msg(h).decode()}")
        elif rc == _ERR_CRASH:
            exc = ReferenceCrash(
                f"message {lib.kme_oracle_err_index(h)}: "
                f"{lib.kme_oracle_err_msg(h).decode()}")
        total = lib.kme_oracle_out_len(h)
        raw = ctypes.string_at(lib.kme_oracle_out_buf(h), total).decode()
        lines = raw.splitlines()
        nproc = lib.kme_oracle_n_processed(h)
        counts = np.ctypeslib.as_array(
            lib.kme_oracle_line_counts(h), shape=(nproc,)).tolist() \
            if nproc else []
        out: List[List[str]] = []
        pos = 0
        for c in counts:
            out.append(lines[pos:pos + c])
            pos += c
        return out, exc

    def dump_state(self) -> str:
        """The engine's complete store state as the checkpoint text
        payload (one record per line; includes position insertion
        stamps so dict iteration order survives a restore)."""
        return self._lib.kme_oracle_dump_state(self._h).decode()

    def load_state(self, text: str) -> None:
        """Replace the five stores with a dump_state() payload."""
        rc = self._lib.kme_oracle_load_state(self._h, text.encode())
        if rc != 0:
            raise ValueError("malformed native-engine state payload")

    def export_state(self) -> dict:
        """Host dict view of the five stores, comparable to
        OracleEngine's dicts (tests/test_native_oracle.py)."""
        raw = self.dump_state()
        balances, positions, orders, books, buckets = {}, {}, {}, {}, {}
        for ln in raw.splitlines():
            parts = ln.split()
            kind = parts[0]
            vals = [int(x) for x in parts[1:]]
            if kind == "B":
                balances[vals[0]] = vals[1]
            elif kind == "P":
                positions[(vals[0], vals[1])] = (vals[2], vals[3])
            elif kind == "K":
                books[vals[0]] = (vals[1], vals[2])
            elif kind == "U":
                buckets[vals[0]] = (vals[1], vals[2])
            elif kind == "O":
                orders[vals[0]] = {
                    "action": vals[1], "aid": vals[2], "sid": vals[3],
                    "price": vals[4], "size": vals[5],
                    "next": vals[7] if vals[6] else None,
                    "prev": vals[9] if vals[8] else None,
                }
        return {"balances": balances, "positions": positions,
                "orders": orders, "books": books, "buckets": buckets}
