// Native quirk-exact matching engine: a C++ port of the scalar oracle
// (kme_tpu/oracle/engine.py — the semantics authority, itself an exact
// replica of /root/reference/src/main/java/KProcessor.java:63-445).
//
// Purpose: quirk-exact serving AT SPEED. The parallel lanes engine is
// provably un-schedulable under Q11 (COMPAT.md) and the serial device
// replica is op-count-bound on TPU, so the fast java-compat path is a
// native host engine — the same role the reference's own JVM+RocksDB
// stack plays. Byte parity with the Python oracle is pinned by
// tests/test_native_oracle.py (wire lines AND deep store state).
//
// Input envelope: ids are Java longs (wrapped at the Python marshal
// boundary), price/size are int32 (EnvelopeError beyond) — the
// Jackson-parseable envelope, COMPAT.md.
//
// Float bit scans (Q7): the reference uses double log10 math; CPython's
// math.log10 and this file's std::log10 are the same libm on this
// platform, so the overshoot behavior matches the oracle bit-for-bit
// (tests sweep the full 126-bit range plus overshoot points).

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <unordered_map>
#include <utility>
#include <vector>

namespace {

constexpr int64_t OP_ADD_SYMBOL = 0, OP_REMOVE_SYMBOL = 1, OP_BUY = 2,
                  OP_SELL = 3, OP_CANCEL = 4, OP_BOUGHT = 5, OP_SOLD = 6,
                  OP_REJECT = 7, OP_CREATE_BALANCE = 100, OP_TRANSFER = 101,
                  OP_PAYOUT = 200;

constexpr int32_t OK = 0, ERR_HANG = 1, ERR_CRASH = 2;

// ---- Java arithmetic (two's complement; unsigned ops dodge UB) ----
inline int64_t jadd(int64_t a, int64_t b) {
  return (int64_t)((uint64_t)a + (uint64_t)b);
}
inline int64_t jmul(int64_t a, int64_t b) {
  return (int64_t)((uint64_t)a * (uint64_t)b);
}
inline int64_t jneg(int64_t a) { return (int64_t)(0ULL - (uint64_t)a); }
inline int64_t jshl(int64_t n, int k) {
  return (int64_t)((uint64_t)n << (k & 63));
}
inline int64_t jshr(int64_t n, int k) { return n >> (k & 63); }  // arithmetic
inline int32_t jint(int64_t x) { return (int32_t)(uint32_t)(uint64_t)x; }

inline bool get_bit(int64_t n, int k) { return 1 == (jshr(n, k) & 1); }
inline int64_t set_bit(int64_t n, int k) { return n | jshl(1, k); }
inline int64_t unset_bit(int64_t n, int k) { return n & ~jshl(1, k); }

// KProcessor.java:371-377 — double log10 scans with Java cast semantics
inline int32_t java_int_of_log_ratio(int64_t v) {
  if (v < 0) return 0;                    // (int) NaN
  if (v == 0) return INT32_MIN;           // (int) -Infinity
  double r = std::log10((double)v) / std::log10(2.0);
  return (int32_t)r;                      // in-range truncation
}
inline int32_t first_set_bit_pos_float(int64_t n) {
  return java_int_of_log_ratio(n & jneg(n));
}
inline int32_t last_set_bit_pos_float(int64_t n) {
  return java_int_of_log_ratio(n);
}

struct Book {  // (msb, lsb) 126-bit bitmap halves
  int64_t msb = 0, lsb = 0;
};
inline int32_t book_min_price(const Book& b) {
  if (b.lsb == 0 && b.msb == 0) return -1;
  if (b.lsb == 0) return first_set_bit_pos_float(b.msb) + 63;
  return first_set_bit_pos_float(b.lsb);
}
inline int32_t book_max_price(const Book& b) {
  if (b.msb == 0 && b.lsb == 0) return -1;
  if (b.msb == 0) return last_set_bit_pos_float(b.lsb);
  return last_set_bit_pos_float(b.msb) + 63;
}
inline bool check_bit(const Book& b, int32_t price) {
  if (price < 63) return get_bit(b.lsb, price);
  return get_bit(b.msb, price - 63);
}
inline Book with_bit_set(Book b, int32_t price) {
  if (price < 63) b.lsb = set_bit(b.lsb, price);
  else b.msb = set_bit(b.msb, price - 63);
  return b;
}
inline Book with_bit_unset(Book b, int32_t price) {
  if (price < 63) b.lsb = unset_bit(b.lsb, price);
  else b.msb = unset_bit(b.msb, price - 63);
  return b;
}

struct StoredOrder {  // KProcessor.java:448-475
  int64_t action, oid, aid, sid;
  int32_t price, size;
  int64_t next = 0, prev = 0;
  bool next_has = false, prev_has = false;
};

struct Bucket {
  int64_t first = 0, last = 0;
};

struct PairHash {
  size_t operator()(const std::pair<int64_t, int64_t>& p) const {
    uint64_t a = (uint64_t)p.first, b = (uint64_t)p.second;
    a ^= b + 0x9e3779b97f4a7c15ULL + (a << 6) + (a >> 2);
    return (size_t)a;
  }
};

using PosKey = std::pair<int64_t, int64_t>;       // (aid, sid)

struct PosVal {  // (amount, available) + insertion stamp: the Python
  int64_t first, second;  // oracle's dict iterates in INSERTION order,
  uint64_t seq = 0;       // which is observable on payout death paths
};

struct Death {  // ReferenceHang / ReferenceCrash surfaced as codes
  int32_t code;
  const char* what;
};

struct Engine {
  bool java;
  bool has_book_slots = false, has_max_fills = false;
  int64_t book_slots = 0, max_fills = 0;

  std::unordered_map<int64_t, int64_t> balances;
  std::unordered_map<PosKey, PosVal, PairHash> positions;
  uint64_t pos_seq = 0;

  // dict semantics: overwriting an existing key keeps its position;
  // a fresh insert (including delete-then-reinsert) goes to the end
  void put_pos(const PosKey& k, int64_t amount, int64_t available) {
    auto it = positions.find(k);
    if (it != positions.end()) {
      it->second.first = amount;
      it->second.second = available;
    } else {
      positions[k] = PosVal{amount, available, ++pos_seq};
    }
  }
  std::unordered_map<int64_t, StoredOrder> orders;
  // resting-order count per (sid, action) — maintained incrementally at
  // every orders-map insert/erase of a DISTINCT record. Powers (a) the
  // envelope's O(1) book_slots check and (b) the necessary-condition
  // gate that makes the per-trade store snapshot RARE (copying five
  // stores per trade is O(open_orders) and explodes on deep books).
  std::unordered_map<std::pair<int64_t, int64_t>, int64_t, PairHash>
      side_cnt;

  void cnt_add(const StoredOrder& r, int64_t d) {
    auto key = std::make_pair(r.sid, r.action);
    auto it = side_cnt.find(key);
    if (it == side_cnt.end()) {
      if (d > 0) side_cnt.emplace(key, d);
    } else {
      it->second += d;
      if (it->second <= 0) side_cnt.erase(it);
    }
  }

  int64_t cnt_get(int64_t sid, int64_t action) const {
    auto it = side_cnt.find(std::make_pair(sid, action));
    return it == side_cnt.end() ? 0 : it->second;
  }
  std::unordered_map<int64_t, Book> books;
  std::unordered_map<int64_t, Bucket> buckets;

  // per-batch outputs
  std::string out;                 // '\n'-joined wire lines
  std::vector<int64_t> line_counts;
  int64_t err_index = -1;
  int32_t err_code = OK;
  std::string err_msg;
  std::string dump;                // state-dump buffer

  // the mutable echo order of the message being processed
  struct Echo {
    int64_t action, oid, aid, sid;
    int32_t price, size;
    int64_t next = 0, prev = 0;
    bool next_has = false, prev_has = false;
  } cur;
  int64_t cur_lines = 0;

  // ---- wire formatting (byte-exact dumps_order) ----
  void emit(const char* key, int64_t action, int64_t oid, int64_t aid,
            int64_t sid, int64_t price, int64_t size, bool next_has,
            int64_t next, bool prev_has, int64_t prev) {
    char buf[320];
    char nb[24], pb[24];
    if (next_has) snprintf(nb, sizeof nb, "%lld", (long long)next);
    else snprintf(nb, sizeof nb, "null");
    if (prev_has) snprintf(pb, sizeof pb, "%lld", (long long)prev);
    else snprintf(pb, sizeof pb, "null");
    int n = snprintf(buf, sizeof buf,
                     "%s {\"action\":%lld,\"oid\":%lld,\"aid\":%lld,"
                     "\"sid\":%lld,\"price\":%lld,\"size\":%lld,"
                     "\"next\":%s,\"prev\":%s}",
                     key, (long long)action, (long long)oid, (long long)aid,
                     (long long)sid, (long long)price, (long long)size, nb,
                     pb);
    out.append(buf, (size_t)n);
    out.push_back('\n');
    cur_lines += 1;
  }

  // ---- key codecs ----
  int64_t order_book_key(int64_t sid, bool is_buy) const {
    if (java) return jmul(sid, is_buy ? 1 : -1);
    return jadd(jmul(sid, 2), is_buy ? 0 : 1);
  }
  int64_t bucket_key(int64_t book_key, int64_t price) const {
    if (java) return jshl(book_key, 8) | price;
    return jadd(jmul(book_key, 256), price);
  }

  // ---- account ledger (KProcessor.java:131-146) ----
  bool create_balance(int64_t aid) {
    if (balances.count(aid)) return false;
    balances[aid] = 0;
    return true;
  }
  bool transfer(int64_t aid, int32_t size) {
    auto it = balances.find(aid);
    // `-size` is Java INT negation (wraps at int32) before the long cmp
    if (it == balances.end() || it->second < (int64_t)jint(-(int64_t)size))
      return false;
    it->second = jadd(it->second, size);
    return true;
  }

  // ---- symbol lifecycle (KProcessor.java:184-198, 335-357) ----
  bool add_symbol(int64_t sid) {
    if (java) {
      if (books.count(sid)) return false;
      books[sid] = Book{};
      books[jneg(sid)] = Book{};
      return true;
    }
    if (sid < 0 || books.count(jmul(sid, 2))) return false;
    books[jmul(sid, 2)] = Book{};
    books[jadd(jmul(sid, 2), 1)] = Book{};
    return true;
  }

  bool remove_all_orders_java(int64_t book_key) {
    auto it = books.find(book_key);
    if (it == books.end()) return false;
    if (book_min_price(it->second) != -1)
      throw Death{ERR_HANG,
                  "removeAllOrders on a non-empty book: Q4 infinite loop"};
    return true;
  }

  void wipe_book_fixed(int64_t book_key) {
    auto it = books.find(book_key);
    if (it == books.end()) return;
    Book book = it->second;
    int32_t price = book_min_price(book);
    while (price != -1) {
      int64_t bk = bucket_key(book_key, price);
      auto bit = buckets.find(bk);
      if (bit == buckets.end())
        throw Death{ERR_CRASH, "NPE: bitmap bit set but bucket missing"};
      Bucket bucket = bit->second;
      buckets.erase(bit);
      int64_t ptr = bucket.first;
      bool has = true;
      while (has) {
        auto oit = orders.find(ptr);
        if (oit == orders.end())
          throw Death{ERR_CRASH, "NPE: linked order missing in wipe"};
        StoredOrder rec = oit->second;
        cnt_add(rec, -1);
        orders.erase(oit);
        post_remove_adjustments(rec);
        has = rec.next_has;
        ptr = rec.next;
      }
      book = with_bit_unset(book, price);
      price = book_min_price(book);
    }
    books[book_key] = book;
  }

  bool remove_symbol(int64_t sid) {
    if (java) {
      if (remove_all_orders_java(sid) || remove_all_orders_java(jneg(sid)))
        return false;
      books.erase(sid);
      books.erase(jneg(sid));
      return true;
    }
    int64_t s = sid < 0 ? jneg(sid) : sid;
    int64_t kb = jmul(s, 2), ks = jadd(jmul(s, 2), 1);
    if (!books.count(kb)) return false;
    wipe_book_fixed(kb);
    wipe_book_fixed(ks);
    books.erase(kb);
    books.erase(ks);
    return true;
  }

  // ---- settlement (KProcessor.java:148-165) ----
  bool payout(int64_t sid, int32_t size) {
    if (!remove_symbol(sid)) return false;
    int64_t match_sid = java ? sid : (sid < 0 ? jneg(sid) : sid);
    bool credit = java || sid >= 0;
    // iterate matches in INSERTION order (the Python oracle's dict
    // order): on a mid-scan ReferenceCrash the set of balances already
    // credited is part of the state-at-death contract
    std::vector<std::pair<uint64_t, PosKey>> matches;
    for (auto& kv : positions)
      if (kv.first.second == match_sid)
        matches.push_back({kv.second.seq, kv.first});
    std::sort(matches.begin(), matches.end());
    for (auto& m : matches) {
      if (credit) {
        auto pit = positions.find(m.second);
        auto bit = balances.find(m.second.first);
        if (bit == balances.end())
          throw Death{ERR_CRASH,
                      "NPE: payout credits account with no balance"};
        bit->second = jadd(bit->second, jmul(pit->second.first, size));
      }
    }
    for (auto& m : matches) positions.erase(m.second);
    return true;
  }

  // ---- risk / margin engine (KProcessor.java:167-182, 325-333) ----
  bool check_balance(int64_t aid, int64_t sid, int32_t price, bool is_buy,
                     int32_t in_size) {
    auto bit = balances.find(aid);
    if (bit == balances.end()) return false;
    int32_t size = jint(jmul(in_size, is_buy ? 1 : -1));
    auto pit = positions.find({aid, sid});
    int64_t available = pit != positions.end() ? pit->second.second : 0;
    int64_t neg_size = (int64_t)jint(-(int64_t)size);
    int64_t adj;
    if (is_buy)
      adj = std::max(std::min(available, (int64_t)0), neg_size);
    else
      adj = std::min(std::max(available, (int64_t)0), neg_size);
    int64_t unit = is_buy ? (int64_t)jint(price)
                          : (int64_t)jint((int64_t)price - 100);
    int64_t risk = jmul(jadd(size, adj), unit);
    if (bit->second < risk) return false;
    bit->second = jadd(bit->second, jneg(risk));
    if (adj != 0) {
      if (pit == positions.end())
        throw Death{ERR_CRASH, "NPE: checkBalance adj-write with no position"};
      pit->second.second = jadd(available, jneg(adj));
    }
    return true;
  }

  void post_remove_adjustments(const StoredOrder& rec) {
    bool is_buy = rec.action == OP_BUY;
    int32_t size = jint(jmul(rec.size, is_buy ? 1 : -1));
    auto pit = positions.find({rec.aid, rec.sid});
    bool has_pos = pit != positions.end();
    PosVal pos = has_pos ? pit->second : PosVal{0, 0};
    int64_t blocked = has_pos ? jadd(pos.first, jneg(pos.second)) : 0;
    int64_t neg_size = (int64_t)jint(-(int64_t)size);
    int64_t adj;
    if (is_buy)
      adj = std::max(std::min(blocked, (int64_t)0), neg_size);
    else
      adj = std::min(std::max(blocked, (int64_t)0), neg_size);
    auto bit = balances.find(rec.aid);
    if (bit == balances.end())
      throw Death{ERR_CRASH, "NPE: margin release for account with no balance"};
    int64_t unit = is_buy ? (int64_t)jint(rec.price)
                          : (int64_t)jint((int64_t)rec.price - 100);
    bit->second = jadd(bit->second, jmul(jadd(size, adj), unit));
    if (adj != 0) {
      if (!has_pos)
        throw Death{ERR_CRASH,
                    "NPE: postRemoveAdjustments adj-write with no position"};
      PosKey target = java ? PosKey{pos.first, pos.second}
                           : PosKey{rec.aid, rec.sid};  // Q11
      put_pos(target, pos.first, jadd(pos.second, adj));
    }
  }

  // ---- matcher hot loop (KProcessor.java:225-263) ----
  bool cross_guard(bool taker_is_buy, int32_t maker_price) const {
    int32_t limit = cur.price;
    if (java) {
      if (cur.size > 0 && taker_is_buy) return maker_price <= limit;
      return maker_price >= limit;
    }
    if (cur.size <= 0) return false;
    return taker_is_buy ? maker_price <= limit : maker_price >= limit;
  }

  void execute_trade(const StoredOrder& maker, int32_t trade_size,
                     bool taker_is_buy) {
    // maker fill at price 0, taker fill at the improvement; maker first
    fill_order(taker_is_buy ? OP_SOLD : OP_BOUGHT, maker.aid, maker.sid, 0,
               trade_size);
    int32_t improvement = jint((int64_t)cur.price - (int64_t)maker.price);
    fill_order(taker_is_buy ? OP_BOUGHT : OP_SOLD, cur.aid, cur.sid,
               improvement, trade_size);
    emit("OUT", taker_is_buy ? OP_SOLD : OP_BOUGHT, maker.oid, maker.aid,
         maker.sid, 0, trade_size, false, 0, false, 0);
    emit("OUT", taker_is_buy ? OP_BOUGHT : OP_SOLD, cur.oid, cur.aid,
         cur.sid, improvement, trade_size, false, 0, false, 0);
  }

  void fill_order(int64_t action, int64_t aid, int64_t sid, int32_t price,
                  int32_t fsize) {
    int32_t size = jint(jmul(fsize, action == OP_BOUGHT ? 1 : -1));
    PosKey key{aid, sid};
    auto pit = positions.find(key);
    if (pit == positions.end()) {
      put_pos(key, size, size);
    } else {
      PosVal pos = pit->second;
      int64_t new_amount = jadd(pos.first, size);
      PosKey target = java ? PosKey{pos.first, pos.second} : key;  // Q11
      if (new_amount == 0) {
        positions.erase(target);
      } else {
        put_pos(target, new_amount, jadd(pos.second, size));
      }
    }
    auto bit = balances.find(aid);
    if (bit == balances.end())
      throw Death{ERR_CRASH, "NPE: fill credits account with no balance"};
    // int*int wraps at int32 BEFORE the long add (KProcessor.java:286)
    bit->second = jadd(bit->second, (int64_t)jint(jmul(size, price)));
  }

  bool try_match() {
    bool taker_is_buy = cur.action == OP_BUY;
    int64_t opp_key = order_book_key(cur.sid, !taker_is_buy);
    auto bkit = books.find(opp_key);
    if (bkit == books.end())
      throw Death{ERR_CRASH, "NPE: opposite book missing in tryMatch"};
    Book bitmap = bkit->second;
    int32_t price_bit =
        taker_is_buy ? book_min_price(bitmap) : book_max_price(bitmap);
    if (price_bit == -1) return false;
    int64_t bk = bucket_key(opp_key, price_bit);
    auto buit = buckets.find(bk);
    if (buit == buckets.end())
      throw Death{ERR_CRASH,
                  "NPE: best-price bucket missing (Q7 overshoot)"};
    Bucket bucket = buit->second;
    int64_t maker_ptr = bucket.first;
    auto oit = orders.find(maker_ptr);
    if (oit == orders.end())
      throw Death{ERR_CRASH, "NPE: bucket head order missing"};
    StoredOrder maker = oit->second;
    while (cross_guard(taker_is_buy, maker.price)) {
      int32_t trade_size = std::min(cur.size, maker.size);
      maker.size = jint((int64_t)maker.size - trade_size);
      cur.size = jint((int64_t)cur.size - trade_size);
      execute_trade(maker, trade_size, taker_is_buy);
      if (maker.size != 0) break;
      {
        auto mit = orders.find(maker.oid);
        if (mit != orders.end()) {
          cnt_add(mit->second, -1);
          orders.erase(mit);  // no-op when absent (RocksDB delete)
        }
      }
      if (!maker.next_has) {
        buckets.erase(bk);
        bitmap = with_bit_unset(bitmap, maker.price);
        books[opp_key] = bitmap;
        price_bit =
            taker_is_buy ? book_min_price(bitmap) : book_max_price(bitmap);
        if (price_bit == -1) return cur.size == 0;
        bk = bucket_key(opp_key, price_bit);
        buit = buckets.find(bk);
        if (buit == buckets.end())
          throw Death{ERR_CRASH,
                      "NPE: best-price bucket missing (Q7 overshoot)"};
        bucket = buit->second;
        maker_ptr = bucket.first;
      } else {
        maker_ptr = maker.next;
      }
      oit = orders.find(maker_ptr);
      if (oit == orders.end())
        throw Death{ERR_CRASH, "NPE: next maker order missing"};
      maker = oit->second;
    }
    // post-loop bucket-head writeback (KProcessor.java:259-261)
    buckets[bk] = {maker_ptr, bucket.last};
    maker.prev_has = false;
    maker.prev = 0;
    orders[maker_ptr] = maker;
    return cur.size == 0;
  }

  // ---- order entry (KProcessor.java:200-223) ----
  bool add_order() {
    if (!java) {
      if (!(0 <= cur.price && cur.price < 126) || cur.size <= 0) return false;
    }
    bool is_buy = cur.action == OP_BUY;
    int64_t bkey = order_book_key(cur.sid, is_buy);
    if (!books.count(bkey)) return false;
    if (!check_balance(cur.aid, cur.sid, cur.price, is_buy, cur.size))
      return false;
    if (try_match()) return true;
    Book book = books[bkey];
    int64_t oid = cur.oid;
    int64_t bk = bucket_key(bkey, cur.price);
    if (!check_bit(book, cur.price)) {
      buckets[bk] = {oid, oid};
      books[bkey] = with_bit_set(book, cur.price);
    } else {
      auto buit = buckets.find(bk);
      if (buit == buckets.end())
        throw Death{ERR_CRASH, "NPE: bitmap bit set but bucket missing"};
      Bucket bucket = buit->second;
      auto lit = orders.find(bucket.last);
      if (lit == orders.end())
        throw Death{ERR_CRASH, "NPE: bucket tail order missing"};
      StoredOrder curr_last = lit->second;
      curr_last.next = oid;
      curr_last.next_has = true;
      cur.prev = curr_last.oid;
      cur.prev_has = true;
      orders[bucket.last] = curr_last;
      buckets[bk] = {bucket.first, oid};
    }
    StoredOrder rec;
    rec.action = cur.action;
    rec.oid = cur.oid;
    rec.aid = cur.aid;
    rec.sid = cur.sid;
    rec.price = cur.price;
    rec.size = cur.size;
    rec.next = cur.next;
    rec.next_has = cur.next_has;
    rec.prev = cur.prev;
    rec.prev_has = cur.prev_has;
    {
      auto old = orders.find(oid);
      if (old != orders.end()) cnt_add(old->second, -1);
    }
    cnt_add(rec, +1);
    orders[oid] = rec;
    return true;
  }

  // ---- cancel path (KProcessor.java:289-323) ----
  bool remove_order(int64_t oid, int64_t aid) {
    auto oit = orders.find(oid);
    if (oit == orders.end() || oit->second.aid != aid) return false;
    StoredOrder rec = oit->second;
    bool is_buy = rec.action == OP_BUY;
    int64_t bkey = order_book_key(rec.sid, is_buy);
    auto bkit = books.find(bkey);
    int64_t bk = bucket_key(bkey, rec.price);
    auto buit = buckets.find(bk);
    if (!rec.prev_has && !rec.next_has) {
      if (bkit == books.end())
        throw Death{ERR_CRASH, "NPE: book missing in removeOrder"};
      buckets.erase(bk);  // no-op when absent
      books[bkey] = with_bit_unset(bkit->second, rec.price);
    } else if (!rec.prev_has) {
      if (buit == buckets.end())
        throw Death{ERR_CRASH, "NPE: bucket missing in removeOrder unlink"};
      buckets[bk] = {rec.next, buit->second.last};
      auto nit = orders.find(rec.next);
      if (nit == orders.end())
        throw Death{ERR_CRASH, "NPE: next order missing in unlink"};
      StoredOrder nxt = nit->second;
      nxt.prev_has = false;
      nxt.prev = 0;
      orders[rec.next] = nxt;
    } else if (!rec.next_has) {
      if (buit == buckets.end())
        throw Death{ERR_CRASH, "NPE: bucket missing in removeOrder unlink"};
      buckets[bk] = {buit->second.first, rec.prev};
      auto pit2 = orders.find(rec.prev);
      if (pit2 == orders.end())
        throw Death{ERR_CRASH, "NPE: prev order missing in unlink"};
      StoredOrder prv = pit2->second;
      prv.next_has = false;
      prv.next = 0;
      orders[rec.prev] = prv;
    } else {
      auto pit2 = orders.find(rec.prev);
      auto nit = orders.find(rec.next);
      if (pit2 == orders.end() || nit == orders.end())
        throw Death{ERR_CRASH, "NPE: neighbor order missing in unlink"};
      StoredOrder prv = pit2->second;
      StoredOrder nxt = nit->second;
      prv.next = rec.next;
      prv.next_has = true;
      nxt.prev = rec.prev;
      nxt.prev_has = true;
      orders[rec.prev] = prv;
      orders[rec.next] = nxt;
    }
    cnt_add(rec, -1);
    orders.erase(oid);
    post_remove_adjustments(rec);
    return true;
  }

  // ---- per-message dispatch (KProcessor.java:95-126) ----
  void process_one() {
    // IN echo of the pre-image
    emit("IN", cur.action, cur.oid, cur.aid, cur.sid, cur.price, cur.size,
         cur.next_has, cur.next, cur.prev_has, cur.prev);
    bool result = false;
    int64_t a = cur.action;
    if (a == OP_ADD_SYMBOL) result = add_symbol(cur.sid);
    else if (a == OP_REMOVE_SYMBOL) result = remove_symbol(cur.sid);
    else if (a == OP_BUY || a == OP_SELL) result = add_order();
    else if (a == OP_CANCEL) result = remove_order(cur.oid, cur.aid);
    else if (a == OP_PAYOUT) {
      bool r = payout(cur.sid, cur.size);
      if (!java) result = r;  // Q5/Q6: java discards the return
    } else if (a == OP_CREATE_BALANCE) result = create_balance(cur.aid);
    else if (a == OP_TRANSFER) result = transfer(cur.aid, cur.size);
    if (!result) cur.action = OP_REJECT;
    emit("OUT", cur.action, cur.oid, cur.aid, cur.sid, cur.price, cur.size,
         cur.next_has, cur.next, cur.prev_has, cur.prev);
  }

  // read-only prediction of the current (fixed-mode) trade's fill
  // count and whether its residual rests — mirrors add_order/try_match
  // with NO mutation, so the capacity envelope can reject without the
  // five-store snapshot (the snapshot cost O(open_orders) per
  // possibly-violating trade and dominated deep-book judging: ~375s
  // for the 105k/slots=8192 headline, round 5). Death conditions
  // return early with no violation: the real path throws identically.
  void plan_trade(int64_t* fills, bool* rests) const {
    *fills = 0;
    *rests = false;
    if (!(0 <= cur.price && cur.price < 126) || cur.size <= 0) return;
    bool is_buy = cur.action == OP_BUY;
    if (!books.count(order_book_key(cur.sid, is_buy))) return;
    // check_balance outcome, read-only
    auto bit = balances.find(cur.aid);
    if (bit == balances.end()) return;
    int32_t size = jint(jmul(cur.size, is_buy ? 1 : -1));
    auto pit = positions.find({cur.aid, cur.sid});
    int64_t available = pit != positions.end() ? pit->second.second : 0;
    int64_t neg_size = (int64_t)jint(-(int64_t)size);
    int64_t adj =
        is_buy ? std::max(std::min(available, (int64_t)0), neg_size)
               : std::min(std::max(available, (int64_t)0), neg_size);
    int64_t unit = is_buy ? (int64_t)jint(cur.price)
                          : (int64_t)jint((int64_t)cur.price - 100);
    if (bit->second < jmul(jadd(size, adj), unit)) return;
    // dry sweep (the try_match walk on local copies)
    int64_t opp_key = order_book_key(cur.sid, !is_buy);
    auto bkit = books.find(opp_key);
    if (bkit == books.end()) return;  // real path: Death
    Book bitmap = bkit->second;
    int32_t remaining = cur.size;
    int32_t price_bit =
        is_buy ? book_min_price(bitmap) : book_max_price(bitmap);
    if (price_bit != -1) {
      int64_t bk = bucket_key(opp_key, price_bit);
      auto buit = buckets.find(bk);
      if (buit == buckets.end()) return;  // real path: Death
      int64_t maker_ptr = buit->second.first;
      auto oit = orders.find(maker_ptr);
      if (oit == orders.end()) return;  // real path: Death
      StoredOrder maker = oit->second;
      while (remaining > 0 && (is_buy ? maker.price <= cur.price
                                      : maker.price >= cur.price)) {
        int32_t trade_size = std::min(remaining, maker.size);
        int32_t maker_left = jint((int64_t)maker.size - trade_size);
        remaining = jint((int64_t)remaining - trade_size);
        (*fills)++;
        if (maker_left != 0) break;
        if (!maker.next_has) {
          bitmap = with_bit_unset(bitmap, maker.price);
          price_bit =
              is_buy ? book_min_price(bitmap) : book_max_price(bitmap);
          if (price_bit == -1) break;
          bk = bucket_key(opp_key, price_bit);
          buit = buckets.find(bk);
          if (buit == buckets.end()) return;  // real path: Death
          maker_ptr = buit->second.first;
        } else {
          maker_ptr = maker.next;
        }
        oit = orders.find(maker_ptr);
        if (oit == orders.end()) return;  // real path: Death
        maker = oit->second;
      }
    }
    *rests = remaining > 0;
  }

  // the capacity envelope (fixed mode): the O(1) necessary-condition
  // gate first, then the read-only dry-run decides the violation
  // EXACTLY — semantics authority is the Python oracle's run-then-
  // rollback (_process_enveloped), pinned equal by
  // tests/test_native_oracle.py
  void process_one_enveloped() {
    bool is_trade = cur.action == OP_BUY || cur.action == OP_SELL;
    if (!is_trade || (!has_book_slots && !has_max_fills)) {
      process_one();
      return;
    }
    int64_t opp_act = cur.action == OP_BUY ? OP_SELL : OP_BUY;
    bool possible = false;
    if (has_max_fills && cnt_get(cur.sid, opp_act) > max_fills)
      possible = true;
    if (has_book_slots && cnt_get(cur.sid, cur.action) >= book_slots)
      possible = true;
    if (!possible) {
      process_one();
      return;
    }
    int64_t wf = 0;
    bool wr = false;
    plan_trade(&wf, &wr);
    bool violated = has_max_fills && wf > max_fills;
    if (!violated && has_book_slots) {
      // the rollback authority checks "order present after the run
      // with matching sid/action" — which a STALE same-oid resting
      // order also satisfies when the trade itself does not rest
      bool stale = false;
      auto it = orders.find(cur.oid);
      if (it != orders.end() && it->second.sid == cur.sid &&
          it->second.action == cur.action)
        stale = true;
      int64_t cnt = cnt_get(cur.sid, cur.action);
      violated = (wr && cnt + 1 > book_slots)
                 || (!wr && stale && cnt > book_slots);
    }
    if (!violated) {
      process_one();
      return;
    }
    emit("IN", cur.action, cur.oid, cur.aid, cur.sid, cur.price,
         cur.size, cur.next_has, cur.next, cur.prev_has, cur.prev);
    emit("OUT", OP_REJECT, cur.oid, cur.aid, cur.sid, cur.price,
         cur.size, cur.next_has, cur.next, cur.prev_has, cur.prev);
  }
};

}  // namespace

extern "C" {

Engine* kme_oracle_new(int32_t java, int32_t has_book_slots,
                       int64_t book_slots, int32_t has_max_fills,
                       int64_t max_fills) {
  Engine* e = new Engine();
  e->java = java != 0;
  e->has_book_slots = has_book_slots != 0;
  e->book_slots = book_slots;
  e->has_max_fills = has_max_fills != 0;
  e->max_fills = max_fills;
  return e;
}

void kme_oracle_free(Engine* e) { delete e; }

int32_t kme_oracle_process(Engine* e, int64_t n, const int64_t* action,
                           const int64_t* oid, const int64_t* aid,
                           const int64_t* sid, const int64_t* price,
                           const int64_t* size, const int64_t* nxt,
                           const uint8_t* nxt_has, const int64_t* prv,
                           const uint8_t* prv_has) {
  e->out.clear();
  e->line_counts.clear();
  e->err_index = -1;
  e->err_code = OK;
  e->err_msg.clear();
  for (int64_t i = 0; i < n; ++i) {
    e->cur = Engine::Echo{action[i], oid[i], aid[i], sid[i],
                          (int32_t)price[i], (int32_t)size[i],
                          nxt[i], prv[i],
                          nxt_has[i] != 0, prv_has[i] != 0};
    e->cur_lines = 0;
    size_t mark = e->out.size();
    try {
      e->process_one_enveloped();
    } catch (const Death& d) {
      // the oracle raises mid-message: records of earlier messages
      // stand, the dying message emits nothing, state stays at death
      e->out.resize(mark);
      e->err_index = i;
      e->err_code = d.code;
      e->err_msg = d.what;
      return d.code;
    }
    e->line_counts.push_back(e->cur_lines);
  }
  return OK;
}

int64_t kme_oracle_err_index(Engine* e) { return e->err_index; }
const char* kme_oracle_err_msg(Engine* e) { return e->err_msg.c_str(); }
const char* kme_oracle_out_buf(Engine* e) { return e->out.c_str(); }
int64_t kme_oracle_out_len(Engine* e) { return (int64_t)e->out.size(); }
const int64_t* kme_oracle_line_counts(Engine* e) {
  return e->line_counts.data();
}
int64_t kme_oracle_n_processed(Engine* e) {
  return (int64_t)e->line_counts.size();
}

// state dump for deep-equality tests: one record per line
const char* kme_oracle_dump_state(Engine* e) {
  std::string& d = e->dump;
  d.clear();
  char buf[256];
  for (auto& kv : e->balances) {
    snprintf(buf, sizeof buf, "B %lld %lld\n", (long long)kv.first,
             (long long)kv.second);
    d += buf;
  }
  for (auto& kv : e->positions) {
    snprintf(buf, sizeof buf, "P %lld %lld %lld %lld %llu\n",
             (long long)kv.first.first, (long long)kv.first.second,
             (long long)kv.second.first, (long long)kv.second.second,
             (unsigned long long)kv.second.seq);
    d += buf;
  }
  for (auto& kv : e->books) {
    snprintf(buf, sizeof buf, "K %lld %lld %lld\n", (long long)kv.first,
             (long long)kv.second.msb, (long long)kv.second.lsb);
    d += buf;
  }
  for (auto& kv : e->buckets) {
    snprintf(buf, sizeof buf, "U %lld %lld %lld\n", (long long)kv.first,
             (long long)kv.second.first, (long long)kv.second.last);
    d += buf;
  }
  for (auto& kv : e->orders) {
    const StoredOrder& r = kv.second;
    snprintf(buf, sizeof buf, "O %lld %lld %lld %lld %lld %lld %d %lld %d %lld\n",
             (long long)kv.first, (long long)r.action, (long long)r.aid,
             (long long)r.sid, (long long)r.price, (long long)r.size,
             r.next_has ? 1 : 0, (long long)r.next, r.prev_has ? 1 : 0,
             (long long)r.prev);
    d += buf;
  }
  return d.c_str();
}

// restore the five stores from a dump (the checkpoint payload).
// Returns 0 on success, 1 on a malformed line.
int32_t kme_oracle_load_state(Engine* e, const char* text) {
  e->balances.clear();
  e->positions.clear();
  e->orders.clear();
  e->books.clear();
  e->buckets.clear();
  e->side_cnt.clear();  // rebuilt by the 'O' lines below
  e->pos_seq = 0;
  const char* p = text;
  while (*p) {
    const char* nl = strchr(p, '\n');
    size_t len = nl ? (size_t)(nl - p) : strlen(p);
    std::string line(p, len);
    p = nl ? nl + 1 : p + len;
    if (line.empty()) continue;
    long long a, b, c, d2, f, g;
    unsigned long long sq;
    int nh, ph;
    switch (line[0]) {
      case 'B':
        if (sscanf(line.c_str(), "B %lld %lld", &a, &b) != 2) return 1;
        e->balances[a] = b;
        break;
      case 'P':
        if (sscanf(line.c_str(), "P %lld %lld %lld %lld %llu", &a, &b, &c,
                   &d2, &sq) != 5)
          return 1;
        e->positions[{a, b}] = PosVal{c, d2, sq};
        if (sq > e->pos_seq) e->pos_seq = sq;
        break;
      case 'K':
        if (sscanf(line.c_str(), "K %lld %lld %lld", &a, &b, &c) != 3)
          return 1;
        e->books[a] = Book{b, c};
        break;
      case 'U':
        if (sscanf(line.c_str(), "U %lld %lld %lld", &a, &b, &c) != 3)
          return 1;
        e->buckets[a] = Bucket{b, c};
        break;
      case 'O': {
        long long oid2, prv2;
        if (sscanf(line.c_str(),
                   "O %lld %lld %lld %lld %lld %lld %d %lld %d %lld", &oid2,
                   &a, &b, &c, &d2, &f, &nh, &g, &ph, &prv2) != 10)
          return 1;
        StoredOrder r;
        r.action = a;
        r.oid = oid2;
        r.aid = b;
        r.sid = c;
        r.price = (int32_t)d2;
        r.size = (int32_t)f;
        r.next_has = nh != 0;
        r.next = g;
        r.prev_has = ph != 0;
        r.prev = prv2;
        {
          auto old = e->orders.find(oid2);
          if (old != e->orders.end()) e->cnt_add(old->second, -1);
        }
        e->cnt_add(r, +1);
        e->orders[oid2] = r;
        break;
      }
      default:
        return 1;
    }
  }
  return 0;
}

}  // extern "C"
