// Native router for the sequential engine (SeqRouter's C++ twin).
//
// The seq engine needs no conflict analysis — routing is pure id
// mapping (dense aid/sid spaces, oid -> lane for cancels, host-reject
// edge semantics identical to runtime/sequencer.py). The Python loop
// costs ~2us/message (~0.8s on the 400k soak); this does the same work
// over columnar int64 arrays in ~tens of ns/message. Semantics
// authority: SeqRouter.route (runtime/seqsession.py); equality pinned
// by tests/test_seq_engine.py.

#include <climits>
#include <cstdint>
#include <unordered_map>
#include <vector>

namespace {

// wire opcodes (kme_tpu/opcodes.py)
constexpr int64_t OP_ADD_SYMBOL = 0, OP_REMOVE_SYMBOL = 1, OP_BUY = 2,
                  OP_SELL = 3, OP_CANCEL = 4, OP_CREATE_BALANCE = 100,
                  OP_TRANSFER = 101, OP_PAYOUT = 200;
// seq lane acts (kme_tpu/engine/seq.py)
constexpr int32_t L_BUY = 1, L_SELL = 2, L_CANCEL = 3, L_CREATE = 4,
                  L_TRANSFER = 5, L_ADD_SYMBOL = 6, L_PAYOUT_YES = 7,
                  L_PAYOUT_NO = 8, L_REMOVE_SYMBOL = 9;

constexpr int32_t RT_OK = 0, RT_CAP_ACCOUNTS = 1, RT_CAP_SYMBOLS = 2;

struct Router {
  int64_t S, A;
  std::unordered_map<int64_t, int32_t> aid_idx;
  std::unordered_map<int64_t, int32_t> sid_lane;
  std::unordered_map<int64_t, int64_t> oid_sid;

  // route outputs (valid until the next call)
  std::vector<int64_t> o_msg, o_oid;
  std::vector<int32_t> o_act, o_aidx, o_price, o_size, o_lane;
  std::vector<int64_t> o_rej;
  int64_t err_value = 0;

  int32_t acct(int64_t aid, bool* ok) {
    auto it = aid_idx.find(aid);
    if (it != aid_idx.end()) return it->second;
    if ((int64_t)aid_idx.size() >= A) {
      *ok = false;
      err_value = aid;
      return 0;
    }
    int32_t idx = (int32_t)aid_idx.size();
    aid_idx.emplace(aid, idx);
    return idx;
  }

  int32_t lane(int64_t sid, bool* ok) {
    auto it = sid_lane.find(sid);
    if (it != sid_lane.end()) return it->second;
    if ((int64_t)sid_lane.size() >= S) {
      *ok = false;
      err_value = sid;
      return 0;
    }
    int32_t l = (int32_t)sid_lane.size();
    sid_lane.emplace(sid, l);
    return l;
  }
};

// splitmix64 finalizer — the shared 64-bit mixer of the group
// assignment below and its Python twin (bridge/front.py _mix64). The
// two MUST stay bit-identical: the front's split decision is part of
// the durable stream (each group replays its own MatchIn), so an
// assignment drift would re-home symbols across a version bump.
inline uint64_t mix64(uint64_t z) {
  z += 0x9E3779B97F4A7C15ull;
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
  return z ^ (z >> 31);
}

// rendezvous (highest-random-weight) choice: every (key, group) pair
// gets an independent score; the max wins. Adding a group moves only
// the keys the new group wins — the consistent-hash property the
// front door needs when N changes.
inline int32_t group_of(uint64_t key, int32_t ngroups, uint64_t salt) {
  int32_t best = 0;
  uint64_t best_score = 0;
  for (int32_t g = 0; g < ngroups; g++) {
    uint64_t score = mix64(key ^ mix64(salt + (uint64_t)g));
    if (g == 0 || score > best_score) {
      best = g;
      best_score = score;
    }
  }
  return best;
}

}  // namespace

extern "C" {

// Columnar group assignment: out[i] = rendezvous group of key[i] among
// ngroups, under `salt` (distinct salts keep the symbol->group and
// account->group spaces independently balanced). Stateless and pure —
// tens of ns/key, same cost profile as kme_router_route.
void kme_group_assign(int64_t n, const int64_t* key, int32_t ngroups,
                      int64_t salt, int32_t* out) {
  if (ngroups <= 1) {
    for (int64_t i = 0; i < n; i++) out[i] = 0;
    return;
  }
  for (int64_t i = 0; i < n; i++)
    out[i] = group_of((uint64_t)key[i], ngroups, (uint64_t)salt);
}

void* kme_router_new(int64_t lanes, int64_t accounts) {
  auto* r = new Router();
  r->S = lanes;
  r->A = accounts;
  return r;
}

void kme_router_free(void* p) { delete static_cast<Router*>(p); }

// Route n messages. Fields arrive as raw int64 values (anything beyond
// int64 never reaches this path: the Python wrapper's array build
// raises OverflowError first and that call falls back to the Python
// router).
// Returns RT_OK or a capacity code (err_value holds the offending id).
int32_t kme_router_route(void* p, int64_t n, const int64_t* action,
                         const int64_t* oid, const int64_t* aid,
                         const int64_t* sid, const int64_t* price,
                         const int64_t* size) {
  Router& r = *static_cast<Router*>(p);
  r.o_msg.clear();
  r.o_oid.clear();
  r.o_act.clear();
  r.o_aidx.clear();
  r.o_price.clear();
  r.o_size.clear();
  r.o_lane.clear();
  r.o_rej.clear();
  r.o_msg.reserve(n);
  bool ok = true;
  auto emit = [&](int64_t i, int32_t act, int32_t aidx, int32_t ln) {
    r.o_msg.push_back(i);
    r.o_act.push_back(act);
    r.o_aidx.push_back(aidx);
    r.o_price.push_back((int32_t)price[i]);
    r.o_size.push_back((int32_t)size[i]);
    r.o_lane.push_back(ln);
    r.o_oid.push_back(oid[i]);
  };
  for (int64_t i = 0; i < n; i++) {
    int64_t a = action[i];
    if (a == OP_BUY || a == OP_SELL) {
      // mutation ORDER matches the Python authority (lane, then
      // oid_sid, then acct) so partial map state after a CapacityError
      // is identical either way (ADVICE r4)
      int32_t ln = r.lane(sid[i], &ok);
      if (!ok) return RT_CAP_SYMBOLS;
      r.oid_sid[oid[i]] = sid[i];
      int32_t ai = r.acct(aid[i], &ok);
      if (!ok) return RT_CAP_ACCOUNTS;
      emit(i, a == OP_BUY ? L_BUY : L_SELL, ai, ln);
    } else if (a == OP_CANCEL) {
      auto it = r.oid_sid.find(oid[i]);
      if (it == r.oid_sid.end()) {
        r.o_rej.push_back(i);
        continue;
      }
      // Python evaluates _acct before _lane here (argument order)
      int32_t ai = r.acct(aid[i], &ok);
      if (!ok) return RT_CAP_ACCOUNTS;
      int32_t ln = r.lane(it->second, &ok);
      if (!ok) return RT_CAP_SYMBOLS;
      emit(i, L_CANCEL, ai, ln);
    } else if (a == OP_CREATE_BALANCE) {
      int32_t ai = r.acct(aid[i], &ok);
      if (!ok) return RT_CAP_ACCOUNTS;
      emit(i, L_CREATE, ai, 0);
    } else if (a == OP_TRANSFER) {
      int32_t ai = r.acct(aid[i], &ok);
      if (!ok) return RT_CAP_ACCOUNTS;
      emit(i, L_TRANSFER, ai, 0);
    } else if (a == OP_ADD_SYMBOL) {
      if (sid[i] < 0) {
        r.o_rej.push_back(i);
        continue;
      }
      int32_t ln = r.lane(sid[i], &ok);
      if (!ok) return RT_CAP_SYMBOLS;
      emit(i, L_ADD_SYMBOL, 0, ln);
    } else if (a == OP_REMOVE_SYMBOL || a == OP_PAYOUT) {
      // abs(INT64_MIN) = 2^63 can never be a (wrapped) Java-long map
      // key, so the Python authority host-rejects it — and negating it
      // here would be signed-overflow UB (same guard as kme_host.cpp)
      if (sid[i] == INT64_MIN) {
        r.o_rej.push_back(i);
        continue;
      }
      int64_t s = sid[i] < 0 ? -sid[i] : sid[i];
      auto it = r.sid_lane.find(s);
      if (it == r.sid_lane.end()) {
        r.o_rej.push_back(i);
        continue;
      }
      int32_t act = a == OP_REMOVE_SYMBOL
                        ? L_REMOVE_SYMBOL
                        : (sid[i] >= 0 ? L_PAYOUT_YES : L_PAYOUT_NO);
      emit(i, act, 0, it->second);
      // resting-oid routes die with the wipe
      for (auto it2 = r.oid_sid.begin(); it2 != r.oid_sid.end();) {
        if (it2->second == s)
          it2 = r.oid_sid.erase(it2);
        else
          ++it2;
      }
    } else {
      r.o_rej.push_back(i);
    }
  }
  return RT_OK;
}

int64_t kme_router_n_routed(void* p) {
  return (int64_t)static_cast<Router*>(p)->o_msg.size();
}
int64_t kme_router_n_rejects(void* p) {
  return (int64_t)static_cast<Router*>(p)->o_rej.size();
}
int64_t kme_router_err_value(void* p) {
  return static_cast<Router*>(p)->err_value;
}
const int64_t* kme_router_o_msg(void* p) {
  return static_cast<Router*>(p)->o_msg.data();
}
const int64_t* kme_router_o_oid(void* p) {
  return static_cast<Router*>(p)->o_oid.data();
}
const int32_t* kme_router_o_act(void* p) {
  return static_cast<Router*>(p)->o_act.data();
}
const int32_t* kme_router_o_aidx(void* p) {
  return static_cast<Router*>(p)->o_aidx.data();
}
const int32_t* kme_router_o_price(void* p) {
  return static_cast<Router*>(p)->o_price.data();
}
const int32_t* kme_router_o_size(void* p) {
  return static_cast<Router*>(p)->o_size.data();
}
const int32_t* kme_router_o_lane(void* p) {
  return static_cast<Router*>(p)->o_lane.data();
}
const int64_t* kme_router_o_rej(void* p) {
  return static_cast<Router*>(p)->o_rej.data();
}

// map export/import (checkpoint contract, mirrors kme_sched_*)
int64_t kme_router_n_accounts(void* p) {
  return (int64_t)static_cast<Router*>(p)->aid_idx.size();
}
int64_t kme_router_n_symbols(void* p) {
  return (int64_t)static_cast<Router*>(p)->sid_lane.size();
}
int64_t kme_router_n_routes(void* p) {
  return (int64_t)static_cast<Router*>(p)->oid_sid.size();
}
void kme_router_export_accounts(void* p, int64_t* keys, int32_t* vals) {
  int64_t i = 0;
  for (auto& kv : static_cast<Router*>(p)->aid_idx) {
    keys[i] = kv.first;
    vals[i] = kv.second;
    i++;
  }
}
void kme_router_export_symbols(void* p, int64_t* keys, int32_t* vals) {
  int64_t i = 0;
  for (auto& kv : static_cast<Router*>(p)->sid_lane) {
    keys[i] = kv.first;
    vals[i] = kv.second;
    i++;
  }
}
void kme_router_export_routes(void* p, int64_t* keys, int64_t* vals) {
  int64_t i = 0;
  for (auto& kv : static_cast<Router*>(p)->oid_sid) {
    keys[i] = kv.first;
    vals[i] = kv.second;
    i++;
  }
}
void kme_router_import_accounts(void* p, int64_t n, const int64_t* keys,
                                const int32_t* vals) {
  auto& m = static_cast<Router*>(p)->aid_idx;
  m.clear();
  for (int64_t i = 0; i < n; i++) m.emplace(keys[i], vals[i]);
}
void kme_router_import_symbols(void* p, int64_t n, const int64_t* keys,
                               const int32_t* vals) {
  auto& m = static_cast<Router*>(p)->sid_lane;
  m.clear();
  for (int64_t i = 0; i < n; i++) m.emplace(keys[i], vals[i]);
}
void kme_router_import_routes(void* p, int64_t n, const int64_t* keys,
                              const int64_t* vals) {
  auto& m = static_cast<Router*>(p)->oid_sid;
  m.clear();
  for (int64_t i = 0; i < n; i++) m.emplace(keys[i], vals[i]);
}

}  // extern "C"
