#!/usr/bin/env bash
# One-command real-broker end-to-end: a REAL Kafka (docker compose) +
# `kme-serve --kafka` + the reference's UNMODIFIED Node harness
# (exchange_test.js / consumer.js / topic.js), diffing the MatchOut
# stream against the quirk-exact oracle's replay of the captured
# MatchIn stream. The harness is unseeded (Math.random), so the oracle
# replays the ACTUAL MatchIn capture rather than a fixture.
#
#   ./run_real_broker_e2e.sh            # full run where prereqs exist
#
# Exits 0 on a clean byte-exact diff, 1 on divergence/failure, and
# 75 (EX_TEMPFAIL) with a SKIP message where docker/node/the reference
# checkout are unavailable (CI environments without docker skip
# cleanly — tests/test_conformance.py pins that skip path).
#
# Reference run order: reference README.md:10-21 (broker, topic.js,
# engine, exchange_test.js, consumer.js).

set -u
HERE="$(cd "$(dirname "$0")" && pwd)"
REPO="$(cd "$HERE/../.." && pwd)"
REF_DIR="${REF_DIR:-/root/reference}"
BOOTSTRAP="${BOOTSTRAP:-localhost:9092}"
WORK="$(mktemp -d)"
COMPOSE="docker compose -f $HERE/docker-compose.yml"

skip() { echo "SKIP: $*" >&2; exit 75; }
fail() { echo "FAIL: $*" >&2; cleanup; exit 1; }
cleanup() {
  [ -n "${SERVE_PID:-}" ] && kill "$SERVE_PID" 2>/dev/null
  $COMPOSE down -v >/dev/null 2>&1
}

# ---- prereqs (missing => clean SKIP, the only path exercisable in
# the build environment, which has no docker daemon or node) ----------
command -v docker >/dev/null 2>&1 || skip "docker not installed"
docker info >/dev/null 2>&1 || skip "docker daemon unavailable"
docker compose version >/dev/null 2>&1 || skip "docker compose v2 missing"
command -v node >/dev/null 2>&1 || skip "node not installed"
[ -f "$REF_DIR/exchange_test.js" ] || skip "reference checkout not at $REF_DIR (set REF_DIR)"
python -c "import aiokafka" 2>/dev/null || skip "aiokafka not installed"
if ! [ -d "$REF_DIR/node_modules/kafkajs" ]; then
  (cd "$REF_DIR" && npm install kafkajs >/dev/null 2>&1) \
    || skip "kafkajs not installed in $REF_DIR and npm install failed"
fi

trap cleanup EXIT

# ---- 1. broker --------------------------------------------------------
$COMPOSE up -d || fail "compose up"
for i in $(seq 60); do
  docker exec conformance-kafka kafka-topics --list \
      --bootstrap-server "$BOOTSTRAP" >/dev/null 2>&1 && break
  sleep 1
  [ "$i" = 60 ] && fail "kafka did not come up"
done

# ---- 2. topics: the reference's own provisioner, UNMODIFIED ----------
(cd "$REF_DIR" && node topic.js) || fail "topic.js"

# ---- 3. engine: kme-serve on the REAL broker -------------------------
(cd "$REPO" && exec python -m kme_tpu.cli serve --kafka "$BOOTSTRAP" \
    --engine seq --compat java --symbols 8 --accounts 128 \
    --slots 8192 --max-fills 128 --batch 1024 \
    --idle-exit 20) &
SERVE_PID=$!

# ---- 4. load: the reference's UNMODIFIED harness ---------------------
(cd "$REF_DIR" && node exchange_test.js) || fail "exchange_test.js"

# wait for the engine to drain and idle-exit
wait "$SERVE_PID" || fail "kme-serve exited non-zero"
SERVE_PID=""

# ---- 5. capture both topics -------------------------------------------
docker exec conformance-kafka kafka-console-consumer \
    --bootstrap-server "$BOOTSTRAP" --topic MatchIn --from-beginning \
    --timeout-ms 10000 > "$WORK/matchin.jsonl" 2>/dev/null
docker exec conformance-kafka kafka-console-consumer \
    --bootstrap-server "$BOOTSTRAP" --topic MatchOut --from-beginning \
    --timeout-ms 10000 --property print.key=true \
    --property key.separator=' ' > "$WORK/matchout.txt" 2>/dev/null
[ -s "$WORK/matchin.jsonl" ] || fail "no MatchIn records captured"

# ---- 6. oracle replay + diff -----------------------------------------
(cd "$REPO" && python -m kme_tpu.cli oracle --compat java) \
    < "$WORK/matchin.jsonl" > "$WORK/expected.txt" || fail "oracle replay"
if diff -u "$WORK/expected.txt" "$WORK/matchout.txt" > "$WORK/diff.txt"; then
  echo "OK: MatchOut byte-exact vs the oracle replay" \
       "($(wc -l < "$WORK/matchout.txt") records)"
  exit 0
fi
echo "DIVERGED — first lines:" >&2
head -20 "$WORK/diff.txt" >&2
exit 1
