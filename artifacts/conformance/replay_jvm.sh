#!/usr/bin/env sh
# Replay a conformance fixture through the real KProcessor and diff.
# Usage: ./replay_jvm.sh <fixture-name> [bootstrap]
# Prereq: broker up (docker-compose.yml), topics created, KProcessor
# running with fresh state stores (see README.md in this directory).
set -eu
NAME="${1:?usage: replay_jvm.sh <fixture> [bootstrap]}"
BOOTSTRAP="${2:-localhost:9092}"
HERE="$(cd "$(dirname "$0")" && pwd)"
IN="$HERE/$NAME.in.jsonl"
WANT="$HERE/$NAME.expected.txt"
[ -f "$IN" ] || { echo "no fixture $IN" >&2; exit 2; }
NLINES=$(wc -l < "$WANT")

echo "producing $(wc -l < "$IN") messages to MatchIn..." >&2
kafka-console-producer --bootstrap-server "$BOOTSTRAP" \
    --topic MatchIn < "$IN"

echo "draining $NLINES lines from MatchOut..." >&2
kafka-console-consumer --bootstrap-server "$BOOTSTRAP" \
    --topic MatchOut --from-beginning --max-messages "$NLINES" \
    --property print.key=true --property key.separator=' ' \
    --timeout-ms 60000 > "/tmp/$NAME.got.txt"

if diff -u "$WANT" "/tmp/$NAME.got.txt"; then
    echo "CONFORMANCE PASS: $NAME byte-exact" >&2
else
    echo "CONFORMANCE FAIL: $NAME diverged (see diff above)" >&2
    exit 1
fi
